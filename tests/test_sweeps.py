"""Sweep-operator parity: pointer-jumping vs dense incidence matmul.

The doubling sweeps are the 10k-bus path (SURVEY.md §7); the dense sweeps
are the small-feeder MXU path already validated against the reference's
``DPF_return7`` behavior in test_ladder.py. Equality of the two operators
on arbitrary trees transfers that validation to the scalable path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from freedm_tpu.grid import cases
from freedm_tpu.pf import ladder, sweeps
from freedm_tpu.utils import cplx


def _rand_c(rng, shape):
    return cplx.as_c(rng.normal(size=shape) + 1j * rng.normal(size=shape))


@pytest.mark.parametrize(
    "feeder",
    [
        cases.vvc_9bus(),
        cases.synthetic_radial(200, seed=1),
        cases.synthetic_radial(64, seed=2, lateral_prob=0.0),  # pure trunk: depth = n
        cases.synthetic_radial(64, seed=3, lateral_prob=1.0),  # star-ish: shallow
    ],
    ids=["9bus", "rand200", "trunk64", "shallow64"],
)
def test_doubling_matches_dense(feeder, rng):
    dtype = jnp.float64
    b_dense, f_dense = sweeps.dense_sweeps(feeder, dtype)
    for maker in (sweeps.doubling_sweeps, sweeps.euler_sweeps):
        b_alt, f_alt = maker(feeder, dtype)
        x = _rand_c(rng, (feeder.n_branches, 3))
        np.testing.assert_allclose(b_alt(x).re, b_dense(x).re, atol=1e-10)
        np.testing.assert_allclose(b_alt(x).im, b_dense(x).im, atol=1e-10)
        np.testing.assert_allclose(f_alt(x).re, f_dense(x).re, atol=1e-10)
        np.testing.assert_allclose(f_alt(x).im, f_dense(x).im, atol=1e-10)


def test_doubling_vmaps(rng):
    feeder = cases.synthetic_radial(100, seed=4)
    dtype = jnp.float64
    b_dense, _ = sweeps.dense_sweeps(feeder, dtype)
    b_dbl, _ = sweeps.doubling_sweeps(feeder, dtype)
    x = _rand_c(rng, (5, feeder.n_branches, 3))
    got = jax.vmap(b_dbl)(x)
    want = jax.vmap(b_dense)(x)
    np.testing.assert_allclose(got.re, want.re, atol=1e-10)
    np.testing.assert_allclose(got.im, want.im, atol=1e-10)


def test_ladder_solution_identical_across_methods():
    feeder = cases.synthetic_radial(300, seed=5)
    solve_dense, _ = ladder.make_ladder_solver(feeder, sweep_method="dense")
    r1 = solve_dense(feeder.s_load)
    assert bool(r1.converged)
    for method in ("doubling", "euler"):
        solve_alt, _ = ladder.make_ladder_solver(feeder, sweep_method=method)
        r2 = solve_alt(feeder.s_load)
        assert bool(r2.converged)
        np.testing.assert_allclose(r2.v_node.re, r1.v_node.re, atol=1e-10)
        np.testing.assert_allclose(r2.v_node.im, r1.v_node.im, atol=1e-10)


def test_large_feeder_uses_euler_and_balances_power():
    # 5k-bus: compiled without a dense subtree matrix; the auto-selected
    # solver (Euler-tour prefix sums) must converge and satisfy
    # conservation: substation injection = total load + total series
    # losses. (2 kW/bus keeps the feeder inside its loadability limit —
    # heavier loading is genuine voltage collapse, where the ladder
    # method diverges by construction.)
    feeder = cases.synthetic_radial(5000, seed=6, pv_frac=0.1, load_kw=2.0)
    assert feeder.subtree is None
    solve, _ = ladder.make_ladder_solver(feeder)
    res = solve(feeder.s_load)
    assert bool(res.converged), float(res.residual)
    p_sub = float(jnp.sum(ladder.substation_power_kva(feeder, res).re))
    p_load = float(jnp.sum(ladder.load_power_kva(feeder, res).re))
    loss = float(ladder.total_loss_kw(feeder, res))
    assert loss == pytest.approx(p_sub - p_load, abs=1e-6)
    # Losses are a small positive fraction of the feeder throughput.
    assert 0 < loss < 0.2 * abs(p_sub)
