"""Profiling registry tests (``freedm_tpu.core.profiling``).

Covers: the compile account keying (one entry per (workload, shape
bucket) no matter how often the shape recompiles), the device-memory
peak's monotonicity, host-path timers, the disabled-by-default no-op
path (the acceptance bar: one attribute check, no recorded state), and
the ``traced_solver``/serve/QSTS integration hooks plus the ``/profile``
route.
"""

import json
import urllib.request

import pytest

from freedm_tpu.core import metrics as M
from freedm_tpu.core import profiling, tracing


@pytest.fixture
def profiler():
    """Enable the process profiler for one test; hard-reset afterwards
    so the rest of the suite runs on the disabled no-op path."""
    profiling.PROFILER.configure(enabled=True)
    yield profiling.PROFILER
    profiling.PROFILER.reset()


# ---------------------------------------------------------------------------
# compile account
# ---------------------------------------------------------------------------


def test_compile_registry_one_entry_per_shape_bucket(profiler):
    # Repeated compiles of the same (workload, bucket) accumulate onto
    # ONE entry; a different bucket opens its own.
    for _ in range(3):
        profiler.record_compile("pf", 8, 0.25)
    profiler.record_compile("pf", 64, 1.0)
    profiler.record_compile("qsts:newton", "S16xT24", 2.0)
    snap = profiler.snapshot()
    assert set(snap["compiles"]) == {"pf", "qsts:newton"}
    assert set(snap["compiles"]["pf"]) == {"8", "64"}
    pf8 = snap["compiles"]["pf"]["8"]
    assert pf8["count"] == 3
    assert pf8["total_s"] == pytest.approx(0.75)
    assert pf8["max_s"] == pytest.approx(0.25)
    # The profile_* metric series carry the same account.
    assert profiling.PROFILE_COMPILES.labels("pf", "8").value == 3
    assert profiling.PROFILE_COMPILE_SECONDS.labels(
        "pf", "8"
    ).value == pytest.approx(0.75)


def test_traced_solver_records_first_call_compile(profiler):
    from freedm_tpu.grid.cases import synthetic_mesh
    from freedm_tpu.pf.newton import make_newton_solver

    sys_ = synthetic_mesh(10, seed=0, load_mw=1.0, chord_frac=1.0)
    solve, _ = make_newton_solver(sys_)
    solve()
    solve()
    solve()
    snap = profiler.snapshot()
    # Only the first call (the synchronous trace+compile hit) lands on
    # the account, keyed (solver, "base"); warm dispatches add nothing.
    assert snap["compiles"]["newton"]["base"]["count"] == 1
    assert snap["compiles"]["newton"]["base"]["total_s"] > 0


def test_solver_under_vmap_records_no_compile(profiler):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from freedm_tpu.grid.cases import synthetic_mesh
    from freedm_tpu.pf.newton import make_newton_solver

    sys_ = synthetic_mesh(10, seed=0, load_mw=1.0, chord_frac=1.0)
    _, solve_fixed = make_newton_solver(sys_, max_iter=4)
    scale = np.random.default_rng(0).uniform(0.9, 1.1, (3, 1))
    p = jnp.asarray(scale * np.asarray(sys_.p_inj)[None, :])
    q = jnp.asarray(scale * np.asarray(sys_.q_inj)[None, :])
    jax.vmap(lambda pi, qi: solve_fixed(p_inj=pi, q_inj=qi))(p, q)
    assert "newton" not in profiler.snapshot()["compiles"]


# ---------------------------------------------------------------------------
# device-memory account
# ---------------------------------------------------------------------------


def test_memory_peak_is_monotone(profiler):
    import jax.numpy as jnp

    keep = [jnp.zeros((64, 64))]
    first = profiler.sample_memory("serve")
    assert first is not None and first > 0
    keep.append(jnp.zeros((256, 256)))
    second = profiler.sample_memory("serve")
    assert second > first
    peak_at_high = profiler.snapshot()["memory"]["serve"]["peak_bytes"]
    assert peak_at_high >= second
    del keep[:]
    third = profiler.sample_memory("serve")
    snap = profiler.snapshot()["memory"]["serve"]
    # Live tracks the drop; the peak never comes down.
    assert snap["live_bytes"] == third < second
    assert snap["peak_bytes"] == peak_at_high
    assert snap["samples"] == 3


# ---------------------------------------------------------------------------
# host-path account
# ---------------------------------------------------------------------------


def test_host_timers_accumulate(profiler):
    profiler.record_host("serve.dispatch", 0.002)
    profiler.record_host("serve.dispatch", 0.004)
    profiler.record_host("qsts.chunk_gap", 0.5)
    snap = profiler.snapshot()["host"]
    assert snap["serve.dispatch"]["count"] == 2
    assert snap["serve.dispatch"]["total_s"] == pytest.approx(0.006)
    assert snap["serve.dispatch"]["mean_s"] == pytest.approx(0.003)
    assert snap["qsts.chunk_gap"]["max_s"] == pytest.approx(0.5)
    h = profiling.PROFILE_HOST_SECONDS.labels("serve.dispatch")
    assert h.count == 2


# ---------------------------------------------------------------------------
# disabled mode: the one-attribute-check contract
# ---------------------------------------------------------------------------


def test_disabled_profiler_records_nothing():
    assert not profiling.PROFILER.enabled
    before = profiling.PROFILE_COMPILES.labels("off", "1").value
    profiling.PROFILER.record_compile("off", 1, 9.9)
    profiling.PROFILER.record_host("off.path", 9.9)
    profiling.PROFILER.record_mesh("off", 4)
    profiling.PROFILER.record_pf_pattern("off", nnz=5, blocks=4)
    assert profiling.PROFILER.sample_memory("off") is None
    snap = profiling.PROFILER.snapshot()
    assert snap == {"enabled": False, "compiles": {}, "memory": {},
                    "host": {}, "mesh_devices": {}, "pf_patterns": {}}
    assert profiling.PROFILE_COMPILES.labels("off", "1").value == before


def test_disabled_mode_solver_path_does_no_profiling_work():
    # The wrapped solver's disabled path must not touch the profiler
    # beyond the enabled check: no compile entries appear even across
    # a genuine first (compile) call.
    from freedm_tpu.grid.cases import synthetic_mesh
    from freedm_tpu.pf.newton import make_newton_solver

    assert not profiling.PROFILER.enabled
    assert not tracing.TRACER.enabled
    sys_ = synthetic_mesh(10, seed=1, load_mw=1.0, chord_frac=1.0)
    solve, _ = make_newton_solver(sys_)
    solve()
    solve()
    assert profiling.PROFILER.snapshot()["compiles"] == {}


# ---------------------------------------------------------------------------
# QSTS + /profile route integration
# ---------------------------------------------------------------------------


def test_qsts_chunks_land_on_compile_account_and_profile_route(profiler):
    from freedm_tpu.scenarios.engine import StudySpec, run_study

    spec = StudySpec(case="vvc_9bus", scenarios=2, steps=6, chunk_steps=4,
                     dt_minutes=15.0, seed=3)
    run_study(spec)
    snap = profiler.snapshot()
    # Full chunk (T4) + ragged tail (T2): one account entry each.
    assert set(snap["compiles"]["qsts:ladder"]) == {"S2xT4", "S2xT2"}
    assert all(
        v["count"] == 1 for v in snap["compiles"]["qsts:ladder"].values()
    )
    # The host gap between the two chunks was timed...
    assert snap["host"]["qsts.chunk_gap"]["count"] >= 1
    # ...memory was sampled per chunk...
    assert snap["memory"]["qsts"]["samples"] >= 2
    # ...and /profile serves the same snapshot.
    server = M.MetricsServer(port=0).start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/profile", timeout=5
        ) as r:
            served = json.loads(r.read())
    finally:
        server.stop()
    assert served["enabled"] is True
    assert served["compiles"]["qsts:ladder"] == snap["compiles"]["qsts:ladder"]
