"""Checkpoint/resume tests (VERDICT r3 item 8).

SURVEY §5: "orbax-style checkpoint of solver/scenario state is a
required addition".  A fleet killed mid-run and restarted with
``--resume`` must CONTINUE its LB/VVC trajectories, not restart them.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from freedm_tpu.cli import build_runtime
from freedm_tpu.core.config import GlobalConfig
from freedm_tpu.devices.manager import DeviceManager
from freedm_tpu.devices.schema import DEFAULT_TYPES

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def write_rig(tmp_path):
    """Config-only rig: seeded fake devices with an LB story (surplus
    node) and VVC actuation rows (Sst_a/b/c on feeder row 4)."""
    lines = ["<root>"]
    for t in DEFAULT_TYPES:
        lines.append(f"  <deviceType><id>{t.id}</id>")
        for s in t.states:
            lines.append(f"    <state>{s}</state>")
        for c in t.commands:
            lines.append(f"    <command>{c}</command>")
        lines.append("  </deviceType>")
    lines.append("</root>")
    (tmp_path / "device.xml").write_text("\n".join(lines))
    devs = [("SST", "Sst", "gateway", 0.0),
            ("DRER", "Drer", "generation", 30.0),
            ("LOAD", "Load", "drain", 10.0)]
    devs += [(f"Q4_{ph}", f"Sst_{ph}", "gateway", 0.0) for ph in "abc"]
    # Second fleet row (non-federate add-host): the demand node the
    # surplus migrates to.
    devs_b = [("SSTB", "Sst", "gateway", 0.0), ("LOADB", "Load", "drain", 20.0)]
    al = ["<root>"]
    for name, owner, dd in (("rig", "", devs), ("rig-b", "nodeB:50811", devs_b)):
        owner_attr = f' owner="{owner}"' if owner else ""
        al.append(f'  <adapter name="{name}" type="fake"{owner_attr}>')
        al.append("    <state>")
        for i, (dev, typ, sig, val) in enumerate(dd):
            al.append(
                f'      <entry index="{i + 1}" value="{val}"><type>{typ}</type>'
                f"<device>{dev}</device><signal>{sig}</signal></entry>"
            )
        al += ["    </state>", "  </adapter>"]
    al.append("</root>")
    (tmp_path / "adapter.xml").write_text("\n".join(al))
    return GlobalConfig(
        add_host=["nodeB:50811"],
        device_config=str(tmp_path / "device.xml"),
        adapter_config=str(tmp_path / "adapter.xml"),
        vvc_case="vvc_9bus",
        migration_step=1.0,
        checkpoint=str(tmp_path / "fleet.ckpt"),
    )


def test_kill_and_resume_continues_trajectories(tmp_path):
    cfg = write_rig(tmp_path)
    rt1 = build_runtime(cfg).start()
    rt1.broker.run(n_rounds=6)
    gw1 = float(rt1.fleet.read_devices()["gateway"][0])
    q1 = np.asarray(rt1.vvc.q_kvar).copy()
    alpha1 = rt1.vvc.alpha
    loss1 = float(rt1.broker.shared["vvc"].loss_after_kw)
    migrations1 = rt1.broker._by_name["lb"].module.total_migrations
    rt1.stop()  # the "kill": all in-process state dies with rt1
    assert os.path.exists(cfg.checkpoint)
    assert gw1 > 0 and np.abs(q1).sum() > 0

    # Fresh stack, same config, resume.
    rt2 = build_runtime(GlobalConfig(**{**cfg.__dict__, "resume": True})).start()
    try:
        assert rt2.broker.round_index == 6
        # VVC warm state continued, not re-initialized.
        np.testing.assert_allclose(np.asarray(rt2.vvc.q_kvar), q1)
        assert rt2.vvc.alpha == pytest.approx(alpha1)
        # The gateway setpoint was re-issued to the (stateless) rig.
        assert float(rt2.fleet.read_devices()["gateway"][0]) == pytest.approx(gw1)
        lb2 = rt2.broker._by_name["lb"].module
        assert lb2.total_migrations == migrations1
        rt2.broker.run(n_rounds=4)
        # Continuation: VVC loss keeps descending from where it was
        # (a restart would jump back to the uncontrolled loss).
        loss2 = float(rt2.broker.shared["vvc"].loss_after_kw)
        assert loss2 <= loss1 + 1e-6, (loss1, loss2)
        # LB continued exporting from gw1, not from zero.
        gw2 = float(rt2.fleet.read_devices()["gateway"][0])
        assert gw2 >= gw1
        assert rt2.broker.round_index == 10
    finally:
        rt2.stop()


def test_checkpoint_rejects_wrong_fleet(tmp_path):
    cfg = write_rig(tmp_path)
    rt = build_runtime(cfg).start()
    rt.broker.run(n_rounds=2)
    rt.stop()
    from freedm_tpu.runtime import checkpoint as ckpt

    state = ckpt.load(cfg.checkpoint)
    state["nodes"] = ["somebody:else"]
    rt2 = build_runtime(cfg)
    with pytest.raises(ValueError, match="checkpoint is for nodes"):
        ckpt.restore_state(state, rt2.broker, rt2.fleet)
    rt2.stop()


def test_restore_slots_reorders_rows():
    from freedm_tpu.devices.adapters.fake import FakeAdapter

    fake = FakeAdapter()
    m = DeviceManager(capacity=8)
    # Registration order differs from the saved layout.
    for name in ("B", "C", "A"):
        m.add_device(name, "Sst", fake)
    fake.reveal_devices()
    m.restore_slots({"A": 0, "B": 1, "C": 2})
    assert (m.row_of("A"), m.row_of("B"), m.row_of("C")) == (0, 1, 2)
    # New devices after restore take untouched rows.
    fake2 = FakeAdapter()
    m.add_device("D", "Sst", fake2)
    fake2.reveal_devices()
    assert m.row_of("D") == 3


def test_restored_gateway_waits_for_defer_reveal(tmp_path):
    """ADVICE r4: a checkpointed gateway must land on defer-reveal
    transports (rtds/opendss reveal devices only after their first
    exchange), not just on fake rigs.  Staged values wait for reveal,
    then issue exactly once."""
    from freedm_tpu.devices.adapters.fake import FakeAdapter
    from freedm_tpu.runtime import Fleet, NodeHandle

    fake = FakeAdapter()
    m = DeviceManager(capacity=4)
    m.add_device("SST", "Sst", fake)
    fleet = Fleet([NodeHandle("a:1", m)])
    fleet.stage_restored_gateways(np.asarray([42.0]))

    # Unrevealed (pre-first-exchange): the write must NOT be dropped.
    fleet.read_devices()
    assert fleet._restore_pending is not None

    fake.reveal_devices()  # the transport's first exchange completes
    fleet.read_devices()
    assert fake.get_state("SST", "gateway") == 42.0
    assert fleet._restore_pending is None

    # Exactly once: later rounds must not re-impose the checkpoint over
    # live module writes.
    fake.set_state("SST", "gateway", 7.0)
    fleet.read_devices()
    assert fake.get_state("SST", "gateway") == 7.0


def test_atomic_save_survives_kill_mid_run(tmp_path):
    """SIGKILL a checkpointing CLI fleet mid-run; the checkpoint on
    disk is a complete, loadable snapshot and a resumed run continues
    past the recorded round."""
    cfg = write_rig(tmp_path)
    cfg_file = tmp_path / "freedm.cfg"
    cfg_file.write_text(
        "add-host = nodeB:50811\n"
        f"device-config = {cfg.device_config}\n"
        f"adapter-config = {cfg.adapter_config}\n"
        "vvc-case = vvc_9bus\nmigration-step = 1\n"
        f"checkpoint = {cfg.checkpoint}\n"
    )
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "freedm_tpu", "-c", str(cfg_file),
         "--summary-every", "5"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env, text=True,
    )
    # Wait for a few rounds' worth of summaries, then kill hard.
    lines = []
    deadline = time.monotonic() + 120
    while len(lines) < 3 and time.monotonic() < deadline:
        line = proc.stdout.readline()
        if line.startswith("{"):
            lines.append(json.loads(line))
    proc.kill()
    proc.wait(timeout=10)
    assert lines, "no summaries before kill"
    from freedm_tpu.runtime import checkpoint as ckpt

    state = ckpt.load(cfg.checkpoint)  # parses -> not torn
    assert state["round_index"] > 0
    # Resume in-process and continue.
    rt = build_runtime(GlobalConfig(**{**cfg.__dict__, "resume": True})).start()
    try:
        start_round = rt.broker.round_index
        assert start_round == state["round_index"]
        rt.broker.run(n_rounds=2)
        assert rt.broker.round_index == start_round + 2
    finally:
        rt.stop()
