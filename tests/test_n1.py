"""SMW N-1 screen tests (VERDICT r4 item 2).

Correctness bar: the rank-2-updated solves must reproduce the per-lane
refactorized FDLF exactly (same iteration, same matrices — SMW is an
identity, not an approximation), and agree with full Newton at
tolerance level on every converged lane.
"""

import jax
import jax.numpy as jnp
import numpy as np

from freedm_tpu.grid.cases import synthetic_mesh
from freedm_tpu.grid.matpower import load_builtin
from freedm_tpu.pf.fdlf import make_fdlf_solver
from freedm_tpu.pf.mfree import make_injection_fn
from freedm_tpu.pf.n1 import make_n1_screen, secure_outages
from freedm_tpu.pf.newton import make_newton_solver, s_calc
from freedm_tpu.grid.bus import ybus_dense

F64 = np.float64


def test_injection_fn_matches_dense_ybus():
    """The branch-wise injection evaluation IS the Ybus matvec."""
    sys30 = load_builtin("case_ieee30")
    inject = make_injection_fn(sys30, F64)
    rng = np.random.default_rng(0)
    theta = jnp.asarray(rng.uniform(-0.3, 0.3, sys30.n_bus))
    v = jnp.asarray(rng.uniform(0.95, 1.05, sys30.n_bus))
    status = jnp.asarray(rng.integers(0, 2, sys30.n_branch).astype(F64))
    p, q = inject(theta, v, status=status)
    y = ybus_dense(sys30, status=status, dtype=F64)
    p_ref, q_ref = s_calc(y, theta, v)
    np.testing.assert_allclose(np.asarray(p), np.asarray(p_ref), atol=1e-12)
    np.testing.assert_allclose(np.asarray(q), np.asarray(q_ref), atol=1e-12)


def test_smw_screen_equals_refactorized_fdlf_case30():
    sys30 = load_builtin("case_ieee30")
    secure = secure_outages(sys30)
    screen = make_n1_screen(sys30, dtype=F64, max_iter=40)
    r = screen(jnp.asarray(secure))
    assert bool(np.all(np.asarray(r.converged)))

    fd, _ = make_fdlf_solver(sys30, dtype=F64, max_iter=60)
    for i, k in enumerate(secure[:8]):  # spot-check lanes, full run is slow
        st = np.ones(sys30.n_branch)
        st[k] = 0.0
        rr = fd(status=jnp.asarray(st))
        np.testing.assert_allclose(
            np.asarray(r.v)[i], np.asarray(rr.v), atol=1e-8
        )
        np.testing.assert_allclose(
            np.asarray(r.theta)[i], np.asarray(rr.theta), atol=1e-8
        )


def test_smw_screen_agrees_with_newton_118():
    sys118 = synthetic_mesh(118, seed=1, load_mw=10.0, chord_frac=1.0)
    secure = secure_outages(sys118)[:40]
    screen = make_n1_screen(sys118, dtype=F64, max_iter=40)
    r = screen(jnp.asarray(secure))
    assert bool(np.all(np.asarray(r.converged)))

    _, solve_fixed = make_newton_solver(sys118, dtype=F64, max_iter=10)
    status = np.ones((len(secure), sys118.n_branch), F64)
    status[np.arange(len(secure)), secure] = 0.0
    rb = jax.jit(jax.vmap(lambda s: solve_fixed(status=s)))(jnp.asarray(status))
    np.testing.assert_allclose(
        np.asarray(r.v), np.asarray(rb.v), atol=5e-7
    )
    np.testing.assert_allclose(
        np.asarray(r.theta), np.asarray(rb.theta), atol=5e-7
    )


def test_smw_screen_handles_pinned_endpoints():
    """Outages of branches touching the slack or PV buses mask their
    update columns; the corrected solve must still be exact."""
    sys30 = load_builtin("case_ieee30")
    pinned = [
        k
        for k in secure_outages(sys30)
        if sys30.bus_type[sys30.from_bus[k]] != 0
        or sys30.bus_type[sys30.to_bus[k]] != 0
    ]
    assert pinned, "case30 has pinned-endpoint branches"
    screen = make_n1_screen(sys30, dtype=F64, max_iter=40)
    r = screen(jnp.asarray(pinned))
    assert bool(np.all(np.asarray(r.converged)))
    fd, _ = make_fdlf_solver(sys30, dtype=F64, max_iter=60)
    st = np.ones(sys30.n_branch)
    st[pinned[0]] = 0.0
    rr = fd(status=jnp.asarray(st))
    np.testing.assert_allclose(np.asarray(r.v)[0], np.asarray(rr.v), atol=1e-8)
