"""SMW N-1 screen tests (VERDICT r4 item 2).

Correctness bar: the rank-2-updated solves must reproduce the per-lane
refactorized FDLF exactly (same iteration, same matrices — SMW is an
identity, not an approximation), and agree with full Newton at
tolerance level on every converged lane.
"""

import jax
import jax.numpy as jnp
import numpy as np

from freedm_tpu.grid.cases import synthetic_mesh
from freedm_tpu.grid.matpower import load_builtin
from freedm_tpu.pf.fdlf import make_fdlf_solver
from freedm_tpu.pf.mfree import make_injection_fn
from freedm_tpu.pf.n1 import make_n1_screen, secure_outages
from freedm_tpu.pf.newton import make_newton_solver, s_calc
from freedm_tpu.grid.bus import ybus_dense

F64 = np.float64


def test_injection_fn_matches_dense_ybus():
    """The branch-wise injection evaluation IS the Ybus matvec."""
    sys30 = load_builtin("case_ieee30")
    inject = make_injection_fn(sys30, F64)
    rng = np.random.default_rng(0)
    theta = jnp.asarray(rng.uniform(-0.3, 0.3, sys30.n_bus))
    v = jnp.asarray(rng.uniform(0.95, 1.05, sys30.n_bus))
    status = jnp.asarray(rng.integers(0, 2, sys30.n_branch).astype(F64))
    p, q = inject(theta, v, status=status)
    y = ybus_dense(sys30, status=status, dtype=F64)
    p_ref, q_ref = s_calc(y, theta, v)
    np.testing.assert_allclose(np.asarray(p), np.asarray(p_ref), atol=1e-12)
    np.testing.assert_allclose(np.asarray(q), np.asarray(q_ref), atol=1e-12)


def test_smw_screen_equals_refactorized_fdlf_case30():
    sys30 = load_builtin("case_ieee30")
    secure = secure_outages(sys30)
    screen = make_n1_screen(sys30, dtype=F64, max_iter=40)
    r = screen(jnp.asarray(secure))
    assert bool(np.all(np.asarray(r.converged)))

    fd, _ = make_fdlf_solver(sys30, dtype=F64, max_iter=60)
    for i, k in enumerate(secure[:8]):  # spot-check lanes, full run is slow
        st = np.ones(sys30.n_branch)
        st[k] = 0.0
        rr = fd(status=jnp.asarray(st))
        np.testing.assert_allclose(
            np.asarray(r.v)[i], np.asarray(rr.v), atol=1e-8
        )
        np.testing.assert_allclose(
            np.asarray(r.theta)[i], np.asarray(rr.theta), atol=1e-8
        )


def test_smw_screen_agrees_with_newton_118():
    sys118 = synthetic_mesh(118, seed=1, load_mw=10.0, chord_frac=1.0)
    secure = secure_outages(sys118)[:40]
    screen = make_n1_screen(sys118, dtype=F64, max_iter=40)
    r = screen(jnp.asarray(secure))
    assert bool(np.all(np.asarray(r.converged)))

    _, solve_fixed = make_newton_solver(sys118, dtype=F64, max_iter=10)
    status = np.ones((len(secure), sys118.n_branch), F64)
    status[np.arange(len(secure)), secure] = 0.0
    rb = jax.jit(jax.vmap(lambda s: solve_fixed(status=s)))(jnp.asarray(status))
    np.testing.assert_allclose(
        np.asarray(r.v), np.asarray(rb.v), atol=5e-7
    )
    np.testing.assert_allclose(
        np.asarray(r.theta), np.asarray(rb.theta), atol=5e-7
    )


def test_smw_screen_handles_pinned_endpoints():
    """Outages of branches touching the slack or PV buses mask their
    update columns; the corrected solve must still be exact."""
    sys30 = load_builtin("case_ieee30")
    pinned = [
        k
        for k in secure_outages(sys30)
        if sys30.bus_type[sys30.from_bus[k]] != 0
        or sys30.bus_type[sys30.to_bus[k]] != 0
    ]
    assert pinned, "case30 has pinned-endpoint branches"
    screen = make_n1_screen(sys30, dtype=F64, max_iter=40)
    r = screen(jnp.asarray(pinned))
    assert bool(np.all(np.asarray(r.converged)))
    fd, _ = make_fdlf_solver(sys30, dtype=F64, max_iter=60)
    st = np.ones(sys30.n_branch)
    st[pinned[0]] = 0.0
    rr = fd(status=jnp.asarray(st))
    np.testing.assert_allclose(np.asarray(r.v)[0], np.asarray(rr.v), atol=1e-8)


def test_smw_delta_solve_matches_dense_refactorization():
    """The public correction solve (ISSUE 10 satellite) against the
    float64 oracle: for random rank-k updates, (A + U Vᵀ)⁻¹ b computed
    via smw_delta_solve must match numpy's dense re-factorization of
    the updated matrix."""
    from freedm_tpu.pf.n1 import smw_delta_solve

    rng = np.random.default_rng(3)
    n = 24
    a = rng.normal(size=(n, n)) + n * np.eye(n)  # well-conditioned base
    lu = jax.scipy.linalg.lu_factor(jnp.asarray(a))
    b = rng.normal(size=n)
    for k in (1, 2, 5):
        u = rng.normal(size=(n, k)) / np.sqrt(n)
        v = rng.normal(size=(n, k)) / np.sqrt(n)
        got = np.asarray(smw_delta_solve(lu, jnp.asarray(u),
                                         jnp.asarray(v), jnp.asarray(b)))
        want = np.linalg.solve(a + u @ v.T, b)
        np.testing.assert_allclose(got, want, rtol=0, atol=1e-10)


def test_smw_delta_solve_precomputed_and_rank0_paths():
    """The two call-site shapes: precomputed z/cap (the N-1 screen's
    build-time Z columns) must equal the from-scratch path exactly, and
    the rank-0 degenerate case (the serving cache's injection-delta
    tier: matrix unchanged) must be the bare base solve."""
    from freedm_tpu.pf.n1 import smw_delta_solve

    rng = np.random.default_rng(7)
    n, k = 16, 2
    a = rng.normal(size=(n, n)) + n * np.eye(n)
    lu = jax.scipy.linalg.lu_factor(jnp.asarray(a))
    b = jnp.asarray(rng.normal(size=n))
    u = jnp.asarray(rng.normal(size=(n, k)) / np.sqrt(n))
    v = jnp.asarray(rng.normal(size=(n, k)) / np.sqrt(n))
    z = jax.scipy.linalg.lu_solve(lu, u)
    cap = jnp.eye(k) + v.T @ z
    full = np.asarray(smw_delta_solve(lu, u, v, b))
    pre = np.asarray(smw_delta_solve(lu, None, v, b, z=z, cap=cap))
    np.testing.assert_allclose(pre, full, rtol=0, atol=1e-13)
    # The structured-Vᵀ hook (the N-1 screen's gather form) must be the
    # same correction: here V's columns are masked one-hots at idx.
    idx = jnp.asarray([3, 11])
    mask = jnp.asarray([1.0, 1.0])
    v_oh = jnp.zeros((n, k)).at[idx, jnp.arange(k)].set(mask)
    dense = np.asarray(smw_delta_solve(lu, u, v_oh, b))
    gather = np.asarray(smw_delta_solve(
        lu, u, None, b,
        # cap is not precomputed here, so vt also sees the [n, k] Z —
        # mask per ROW for matrices, per element for vectors.
        vt=lambda x: x[idx] * (mask[:, None] if x.ndim == 2 else mask)))
    np.testing.assert_allclose(gather, dense, rtol=0, atol=1e-13)
    rank0 = np.asarray(smw_delta_solve(lu, None, None, b))
    want0 = np.linalg.solve(a, np.asarray(b))
    np.testing.assert_allclose(rank0, want0, rtol=0, atol=1e-10)
