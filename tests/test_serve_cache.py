"""Incremental serving tier tests (``freedm_tpu.serve.cache``,
ISSUE 10): tier ladder correctness under churn (delta answers within
solver tolerance of full solves, residual fall-through), invalidation
on topology mutation (stale entry never served), LRU+TTL eviction under
a tiny byte budget, single-flight population (a cold herd solves once;
a failed leader fails its followers typed), byte-identity of the
``--serve-pipeline-depth 0`` oracle with caching on, and the GL006
cache-lock ↔ queue-condition acyclicity cross-check.
"""

import dataclasses
import json
import threading
import time

import numpy as np
import pytest

from freedm_tpu.core import metrics as M
from freedm_tpu.grid.matpower import load_builtin
from freedm_tpu.serve import ServeConfig, ServeError, Service
from freedm_tpu.serve.cache import (
    ServeCache,
    injection_digest,
    topology_digest,
)
from freedm_tpu.serve.service import PowerFlowRequest

BUCKETS = (1, 2, 4)
T = 300  # generous per-request timeout: first touches compile


def _cfg(**kw):
    base = dict(max_batch=4, max_wait_ms=5.0, queue_depth=64,
                buckets=BUCKETS)
    base.update(kw)
    return ServeConfig(**base)


@pytest.fixture(scope="module")
def svc():
    s = Service(_cfg())
    # Prime the base case once: every test below starts from a warm
    # engine + a populated base entry.
    r = s.request("pf", PowerFlowRequest(case="case14", timeout_s=T))
    assert r.converged and r.batch.tier == "full"
    yield s
    s.stop()


@pytest.fixture(scope="module")
def cold_svc():
    """The cache-off reference service the correctness tests compare
    against (every request here is a full solve)."""
    s = Service(_cfg(cache_mb=0.0))
    yield s
    s.stop()


def _base_inj(svc):
    eng = svc.engine("pf", "case14")
    return np.array(eng._p0), np.array(eng._q0)


# ---------------------------------------------------------------------------
# tier ladder
# ---------------------------------------------------------------------------


def test_exact_hit_serves_from_cache_without_dispatch(svc):
    before = M.SERVE_BATCH_LANES.labels("pf").count
    r1 = svc.request("pf", PowerFlowRequest(case="case14", timeout_s=T))
    r2 = svc.request("pf", PowerFlowRequest(case="case14", timeout_s=T,
                                            return_state=True))
    assert r1.batch.tier == "exact" and r2.batch.tier == "exact"
    assert r1.batch.bucket == 0 and r1.batch.solve_ms == 0.0
    # No batch was dispatched for either answer...
    assert M.SERVE_BATCH_LANES.labels("pf").count == before
    # ...and the answer is the solved solution, state included on ask.
    assert r2.converged and len(r2.v) == 14
    assert r1.iterations == r2.iterations
    assert svc.stats()["cache"]["hits"]["exact"] >= 2


def test_delta_hits_match_full_solves_across_random_deltas(svc, cold_svc):
    """Churn correctness: random small-rank injection deltas answered by
    the delta tier agree with cache-off full solves to solver tolerance,
    and every delta answer carries a verified residual."""
    p0, q0 = _base_inj(svc)
    rng = np.random.default_rng(11)
    served_delta = 0
    for trial in range(5):
        p = p0.copy()
        q = q0.copy()
        for j in rng.choice(14, size=rng.integers(1, 4), replace=False):
            p[j] += rng.uniform(-0.05, 0.05)
            q[j] += rng.uniform(-0.02, 0.02)
        req = dict(case="case14", p_inj=p.tolist(), q_inj=q.tolist(),
                   return_state=True, timeout_s=T)
        warm = svc.request("pf", PowerFlowRequest(**req))
        full = cold_svc.request("pf", PowerFlowRequest(**req))
        assert warm.converged and full.converged
        if warm.batch.tier == "delta":
            served_delta += 1
            assert warm.residual_pu <= 1e-8  # host-verified, not claimed
        assert np.max(np.abs(np.array(warm.v) - np.array(full.v))) < 1e-6
        assert np.max(np.abs(np.array(warm.theta)
                             - np.array(full.theta))) < 1e-6
    assert served_delta >= 4  # the ladder actually exercised tier 2


def test_delta_residual_fallthrough_never_serves_unverified(svc):
    """An impossible verify bar forces every delta attempt to fall
    through: the answer must come from a full (warm-seeded) solve, and
    the delta-hit counter must not move."""
    p0, q0 = _base_inj(svc)
    p = p0.copy()
    p[2] += 0.031
    cache = svc.cache
    before = dict(svc.stats()["cache"]["hits"])
    old_tol = cache.verify_tol
    cache.verify_tol = 1e-300
    try:
        r = svc.request("pf", PowerFlowRequest(
            case="case14", p_inj=p.tolist(), q_inj=q0.tolist(), timeout_s=T))
    finally:
        cache.verify_tol = old_tol
    assert r.converged and r.batch.tier == "full"
    after = svc.stats()["cache"]["hits"]
    assert after["delta"] == before["delta"]
    assert after["warm"] == before["warm"] + 1  # seeded, solved, verified


def test_warm_tier_seeds_and_cuts_iterations(svc, cold_svc):
    """A delta too large for tier 2 (every bus moved) still wins: the
    full solve is seeded from the nearest cached solution and converges
    in fewer Newton iterations than the cold flat start."""
    warm = svc.request("pf", PowerFlowRequest(case="case14", scale=1.35,
                                              timeout_s=T))
    cold = cold_svc.request("pf", PowerFlowRequest(case="case14", scale=1.35,
                                                   timeout_s=T))
    assert warm.converged and cold.converged
    assert warm.batch.tier == "full"
    assert warm.iterations < cold.iterations
    assert svc.stats()["cache"]["hits"]["warm"] >= 1


def test_client_supplied_seed_bypasses_cache_both_ways():
    """A request carrying its own v0/theta0 is steering the solver
    (possibly toward a different solution branch): the cache must
    neither answer it NOR publish its steered solution under an
    injections-only digest for flat-start clients to hit later."""
    svc3 = Service(_cfg(delta_max_rank=0))  # no delta tier: seeds matter
    try:
        r0 = svc3.request("pf", PowerFlowRequest(
            case="case14", return_state=True, timeout_s=T))
        before = svc3.stats()["cache"]
        seeded = PowerFlowRequest(case="case14", scale=1.28, v0=r0.v,
                                  theta0=r0.theta, timeout_s=T)
        r = svc3.request("pf", seeded)
        assert r.converged and r.batch.tier == "full"
        after = svc3.stats()["cache"]
        # No lookup was recorded at all: the tier ladder never ran.
        assert after["hits"] == before["hits"]
        assert after["misses"] == before["misses"]
        # ...and the steered solution was NOT inserted: the same
        # injections without seeds miss (full solve), then hit.
        flat = PowerFlowRequest(case="case14", scale=1.28, timeout_s=T)
        assert svc3.request("pf", flat).batch.tier == "full"
        assert svc3.request("pf", flat).batch.tier == "exact"
    finally:
        svc3.stop()


# ---------------------------------------------------------------------------
# invalidation / eviction
# ---------------------------------------------------------------------------


def test_topology_mutation_means_stale_entry_unreachable():
    """The cache key carries a topology digest: a mutated-status case (a
    branch reactance bumped — an outage baked into the table) resolves
    to a DIFFERENT entry, so the stale solution cannot be served."""
    sys14 = load_builtin("case14")
    mutated = dataclasses.replace(
        sys14, x=np.array(sys14.x) * np.where(
            np.arange(sys14.n_branch) == 3, 1e6, 1.0)
    )
    assert topology_digest(sys14) != topology_digest(mutated)
    cache = ServeCache(max_bytes=32 << 20)
    e1 = cache.entry("case14", sys14, "dense")
    p, q = np.array(sys14.p_inj), np.array(sys14.q_inj)
    dig = injection_digest(p, q)
    cache.insert(e1, dig, p, q, np.ones(14), np.zeros(14), p, q, 3,
                 1e-10, True)
    assert cache.lookup(e1, dig, p, q)[0] == "exact"
    e2 = cache.entry("case14", mutated, "dense")
    assert e2 is not e1 and e2.key != e1.key
    tier, _ = cache.lookup(e2, dig, p, q)
    assert tier == "miss"  # the stale solution is unreachable


def test_service_invalidate_drops_entries(svc):
    svc.request("pf", PowerFlowRequest(case="case14", timeout_s=T))
    assert svc.stats()["cache"]["solutions"] >= 1
    dropped = svc.cache.invalidate("case14")
    assert dropped >= 1
    r = svc.request("pf", PowerFlowRequest(case="case14", timeout_s=T))
    assert r.batch.tier == "full"  # nothing stale survived to answer
    assert svc.stats()["cache"]["evictions"]["invalidate"] >= 1


def test_lru_eviction_under_tiny_budget():
    """A budget with room for the artifacts plus ~2 solutions: inserting
    a ladder of distinct solutions must evict LRU-first and keep the
    byte accounting under the budget."""
    sys14 = load_builtin("case14")
    cache = ServeCache(max_bytes=5500)  # artifacts ~3.4 KB + ~2 solutions
    ent = cache.entry("case14", sys14, "dense")
    assert ent is not None and ent.artifact_bytes > 0
    p0, q0 = np.array(sys14.p_inj), np.array(sys14.q_inj)
    digs = []
    for i in range(6):
        p = p0 + 0.01 * (i + 1)
        d = injection_digest(p, q0)
        digs.append(d)
        cache.insert(ent, d, p, q0, np.ones(14), np.zeros(14), p, q0,
                     3, 1e-10, True)
        assert cache.bytes <= cache.max_bytes
    st = cache.stats()
    assert st["evictions"]["lru"] >= 4
    assert cache.lookup(ent, digs[0], p0 + 0.01, q0)[0] != "exact"  # evicted
    # The most recent survivor is still exact-servable.
    assert cache.lookup(ent, digs[-1], p0 + 0.06, q0)[0] == "exact"


def test_over_budget_case_is_never_cached():
    sys14 = load_builtin("case14")
    cache = ServeCache(max_bytes=1024)  # under the two-LU artifact cost
    assert cache.entry("case14", sys14, "dense") is None


def test_ttl_expiry_evicts_at_next_touch():
    sys14 = load_builtin("case14")
    cache = ServeCache(max_bytes=32 << 20, ttl_s=0.05)
    ent = cache.entry("case14", sys14, "dense")
    p, q = np.array(sys14.p_inj), np.array(sys14.q_inj)
    dig = injection_digest(p, q)
    cache.insert(ent, dig, p, q, np.ones(14), np.zeros(14), p, q, 3,
                 1e-10, True)
    assert cache.lookup(ent, dig, p, q)[0] == "exact"
    time.sleep(0.08)
    tier, _ = cache.lookup(ent, dig, p, q)
    assert tier == "miss"
    assert cache.stats()["evictions"]["ttl"] >= 1


# ---------------------------------------------------------------------------
# single flight
# ---------------------------------------------------------------------------


def test_cold_herd_populates_once():
    """N concurrent identical requests on a cold digest: one leader
    solves, the rest join its flight — exactly one pf batch dispatches
    and every waiter gets the same answer."""
    # delta_max_rank=0: on a 14-bus case EVERY small delta is
    # rank-eligible, and a delta answer would (correctly) avoid the
    # dispatch this test counts — force the herd onto the full path.
    svc2 = Service(_cfg(delta_max_rank=0))
    try:
        svc2.request("pf", PowerFlowRequest(case="case14", timeout_s=T))
        before = M.SERVE_BATCH_LANES.labels("pf").count
        req = PowerFlowRequest(case="case14", scale=0.93, timeout_s=T)
        n = 6
        barrier = threading.Barrier(n)
        results = [None] * n

        def worker(i):
            barrier.wait(timeout=60)
            results[i] = svc2.request("pf", req)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=T)
        assert all(r is not None and r.converged for r in results)
        vals = {(r.iterations, r.residual_pu, r.v_min_pu) for r in results}
        assert len(vals) == 1  # everyone got the leader's solution
        assert M.SERVE_BATCH_LANES.labels("pf").count == before + 1
        st = svc2.stats()["cache"]
        # A worker that classifies AFTER the leader's publish lands a
        # (legal) late exact hit instead of a flight join — under a
        # loaded runner that race is real, so the herd invariant is
        # joins-plus-late-exacts, with the single-dispatch assert above
        # carrying the "populates once" guarantee either way.
        exacts = st["hits"]["exact"]
        assert st["flight_joins"] + exacts >= n - 1
        tiers = sorted(r.batch.tier for r in results)
        assert tiers.count("full") == 1 and tiers.count("exact") == n - 1
    finally:
        svc2.stop()


def test_flight_followers_fail_with_their_leader():
    """A follower never occupies queue depth — and never hangs: the
    leader's typed failure propagates to everyone riding it."""
    svc2 = Service(_cfg(), start=False)
    try:
        req = PowerFlowRequest(case="case14", timeout_s=T)
        f_lead = svc2.submit("pf", req)
        f_join = svc2.submit("pf", req)
        assert svc2.queue.depth_lanes == 1  # the follower is parked, not queued
        eng = svc2.engine("pf", "case14")
        eng.solve = lambda batch: (_ for _ in ()).throw(
            RuntimeError("injected cold-solve crash"))
        svc2.start()
        for f in (f_lead, f_join):
            with pytest.raises(ServeError) as ei:
                f.result(timeout=T)
            assert ei.value.code == "internal"
    finally:
        svc2.stop()


def test_invalidate_mid_flight_insert_lands_nowhere():
    """Invalidation while a flight is queued: the waiters are still
    answered (leader full, follower exact) but the solve's insert lands
    nowhere — the scatter path peeks, never rebuilds, so no stale-keyed
    entry reappears and no artifact factorization runs on the executor
    lane."""
    svc2 = Service(_cfg(delta_max_rank=0), start=False)
    try:
        req = PowerFlowRequest(case="case14", timeout_s=T)
        f_lead = svc2.submit("pf", req)
        f_join = svc2.submit("pf", req)
        assert svc2.cache.invalidate("case14") == 0  # entries, no solutions
        svc2.start()
        r_lead = f_lead.result(timeout=T)
        r_join = f_join.result(timeout=T)
        assert r_lead.converged and r_join.converged
        assert r_lead.batch.tier == "full"
        assert r_join.batch.tier == "exact" and r_join.batch.bucket == 0
        st = svc2.stats()["cache"]
        assert st["entries"] == 0 and st["solutions"] == 0
    finally:
        svc2.stop()


# ---------------------------------------------------------------------------
# pipeline-oracle equivalence, artifacts, stats, locks
# ---------------------------------------------------------------------------


def _strip_batch(resp) -> str:
    d = resp.to_dict()
    tier = d.pop("batch")["tier"]
    return json.dumps({"tier": tier, **d}, sort_keys=True)


def test_depth0_oracle_byte_identity_with_cache_on():
    """The same sequential request ladder (cold, exact, delta, warm)
    through the pipelined path and the --serve-pipeline-depth 0 oracle,
    both with caching on: identical responses AND identical tiers."""
    svc_pipe = Service(_cfg(pipeline_depth=2))
    svc_ser = Service(_cfg(pipeline_depth=0))
    try:
        p0 = np.array(svc_pipe.engine("pf", "case14")._p0)
        q0 = np.array(svc_pipe.engine("pf", "case14")._q0)
        p_d = p0.copy()
        p_d[4] += 0.02
        ladder = [
            PowerFlowRequest(case="case14", timeout_s=T),
            PowerFlowRequest(case="case14", timeout_s=T),  # exact
            PowerFlowRequest(case="case14", p_inj=p_d.tolist(),
                             q_inj=q0.tolist(), return_state=True,
                             timeout_s=T),  # delta
            PowerFlowRequest(case="case14", scale=1.35, timeout_s=T),  # warm
        ]
        got_p = [_strip_batch(svc_pipe.request("pf", r)) for r in ladder]
        got_s = [_strip_batch(svc_ser.request("pf", r)) for r in ladder]
        assert got_p == got_s
        assert [json.loads(g)["tier"] for g in got_p] == [
            "full", "exact", "delta", "full"]
    finally:
        svc_pipe.stop()
        svc_ser.stop()


def test_entry_artifacts_shared_and_dc_reuses_b_prime():
    """The entry's DC screen attaches without a second B′ factorization
    (make_dc_solver's lu= reuse): no dc.factorize host timer fires, and
    the screen solves sanely off the shared factors."""
    from freedm_tpu.core import profiling

    sys14 = load_builtin("case14")
    cache = ServeCache(max_bytes=32 << 20)
    ent = cache.entry("case14", sys14, "dense")
    profiling.PROFILER.configure(enabled=True)
    try:
        dc = ent.dc_solver()
        assert ent.dc_solver() is dc  # built once
        host = profiling.PROFILER.snapshot()["host"]
        assert "dc.factorize" not in host  # the cached LU was reused
        r = dc.solve()
        theta = np.asarray(r.theta)
        assert np.all(np.isfinite(theta)) and theta.shape == (14,)
    finally:
        profiling.PROFILER.reset()


def test_prewarm_compiles_delta_program():
    svc2 = Service(_cfg(prewarm=("pf/case14",)))
    try:
        ent = svc2.cache.entry(
            "case14", svc2.engine("pf", "case14")._sys, "dense")
        assert ent is not None and ent.delta_fn is not None
    finally:
        svc2.stop()


def test_stats_and_http_expose_cache_block(svc):
    st = svc.stats()["cache"]
    assert st["enabled"] is True
    for key in ("bytes", "budget_bytes", "entries", "solutions", "hits",
                "misses", "evictions", "hit_ratio", "flight_joins"):
        assert key in st
    assert st["bytes"] <= st["budget_bytes"]
    # Disabled config reports itself honestly.
    svc_off = Service(_cfg(cache_mb=0.0), start=False)
    assert svc_off.stats()["cache"] == {"enabled": False}
    svc_off.stop()


def test_debuglock_cache_lock_queue_condition_acyclic():
    """ISSUE 10 satellite: the cache lock and the admission queue's
    condition never nest in either direction (lookup happens before
    put; scatter-side inserts happen outside the queue), and the
    observed order composes acyclically with GL006's static graph."""
    import pathlib

    from freedm_tpu.core.debuglock import DebugLock, LockOrderRecorder
    from freedm_tpu.tools.gridlint import run_lint

    rec = LockOrderRecorder()
    cond_name = "freedm_tpu/serve/queue.py:AdmissionQueue._cond"
    cache_name = "freedm_tpu/serve/cache.py:ServeCache._lock"
    svc2 = Service(_cfg(), start=False)
    svc2.queue._cond = threading.Condition(
        lock=DebugLock(cond_name, recorder=rec))
    svc2.cache._lock = DebugLock(cache_name, recorder=rec)
    try:
        svc2.start()
        p0 = np.array(svc2.engine("pf", "case14")._p0)
        q0 = np.array(svc2.engine("pf", "case14")._q0)
        svc2.request("pf", PowerFlowRequest(case="case14", timeout_s=T))
        svc2.request("pf", PowerFlowRequest(case="case14", timeout_s=T))
        p_d = p0.copy()
        p_d[1] += 0.02
        svc2.request("pf", PowerFlowRequest(
            case="case14", p_inj=p_d.tolist(), q_inj=q0.tolist(),
            timeout_s=T))
        threads = [
            threading.Thread(target=lambda: svc2.request(
                "pf", PowerFlowRequest(case="case14", scale=0.97,
                                       timeout_s=T)))
            for _ in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=T)
    finally:
        svc2.stop()

    observed = rec.snapshot_edges()
    assert rec.acquisitions > 0
    assert (cache_name, cond_name) not in observed
    assert (cond_name, cache_name) not in observed

    root = pathlib.Path(__file__).resolve().parent.parent
    static = run_lint(
        [str(root / "freedm_tpu" / d) for d in ("serve", "scenarios",
                                                "core")],
        root=str(root),
    )
    static_edges = {
        tuple(e) for e in static.artifacts["lock_graph"]["edges"]
    }
    union = observed | static_edges
    assert LockOrderRecorder.find_cycle(union) is None, (
        "observed cache lock order contradicts the GL006 static graph"
    )


# ---------------------------------------------------------------------------
# Mixed-precision delta solves (--pf-precision mixed on the cache tier)
# ---------------------------------------------------------------------------


def test_delta_mixed_precision_verified_by_f64_oracle():
    """``precision="mixed"`` runs the delta tier's inner triangular
    solves in f32 (iterative refinement over an f32 LU copy); the host
    float64 residual verify stays the acceptance oracle, so a served
    mixed delta answer clears the SAME engine tolerance as f64."""
    sys_ = load_builtin("case_ieee30")
    from freedm_tpu.pf.newton import make_newton_solver

    solve, _ = make_newton_solver(sys_)
    r = solve()
    p0 = np.asarray(sys_.p_inj, np.float64)
    q0 = np.asarray(sys_.q_inj, np.float64)
    answers = {}
    for prec in ("mixed", "f64"):
        cache = ServeCache(max_bytes=64 << 20, precision=prec)
        entry = cache.entry("case_ieee30", sys_, "dense")
        assert entry.precision == prec
        cache.insert(
            entry, injection_digest(p0, q0), p0, q0,
            np.asarray(r.v), np.asarray(r.theta), np.asarray(r.p),
            np.asarray(r.q), int(np.asarray(r.iterations)),
            float(np.asarray(r.mismatch)), True,
        )
        p1 = p0.copy()
        p1[5] += 0.01
        tier, near = cache.lookup(entry, injection_digest(p1, q0), p1, q0)
        assert tier == "delta"
        ans = cache.delta_answer(entry, near, p1, q0)
        assert ans is not None, f"{prec} delta fell through"
        # The verify residual IS the host f64 oracle — both precisions
        # must clear the same engine tolerance.
        assert ans["mismatch"] <= entry.tol
        answers[prec] = ans
    # Mixed and f64 agree to solver tolerance (not bit-for-bit).
    dv = float(np.max(np.abs(answers["mixed"]["v"] - answers["f64"]["v"])))
    assert dv < 1e-6, dv


def test_delta_mixed_fallthrough_on_verify_miss():
    """A verify bar the mixed candidate cannot clear must fall through
    (None -> warm tier), never serve unverified — the mixed path keeps
    the fall-through contract intact."""
    sys_ = load_builtin("case_ieee30")
    from freedm_tpu.pf.newton import make_newton_solver

    solve, _ = make_newton_solver(sys_)
    r = solve()
    p0 = np.asarray(sys_.p_inj, np.float64)
    q0 = np.asarray(sys_.q_inj, np.float64)
    cache = ServeCache(max_bytes=64 << 20, precision="mixed",
                       verify_tol=1e-16)
    entry = cache.entry("case_ieee30", sys_, "dense")
    cache.insert(
        entry, injection_digest(p0, q0), p0, q0,
        np.asarray(r.v), np.asarray(r.theta), np.asarray(r.p),
        np.asarray(r.q), 3, 1e-10, True,
    )
    p1 = p0.copy()
    p1[5] += 0.01
    tier, near = cache.lookup(entry, injection_digest(p1, q0), p1, q0)
    assert tier == "delta"
    assert cache.delta_answer(entry, near, p1, q0) is None


def test_cache_precision_resolves_and_validates():
    from freedm_tpu.serve.cache import ServeCache as SC

    assert SC(max_bytes=1 << 20, precision="auto").precision in (
        "f64", "mixed",
    )
    with pytest.raises(ValueError):
        SC(max_bytes=1 << 20, precision="nope")
