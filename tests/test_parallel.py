"""Multi-chip paths on the virtual 8-device CPU mesh.

Checks that the sharded execution (GSPMD-annotated superstep, explicit
shard_map collectives) produces bit-identical results to the replicated
kernels — the correctness contract that lets the same code scale from
one chip to a pod.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from freedm_tpu.grid import cases
from freedm_tpu.modules import gm, lb
from freedm_tpu.parallel import collectives
from freedm_tpu.parallel.mesh import make_mesh, node_sharding
from freedm_tpu.parallel.superstep import make_superstep


@pytest.fixture(scope="module")
def mesh8():
    # conftest forces 8 virtual CPU devices, but CI re-runs this file
    # under a 4-device XLA_FLAGS override (the mesh scale-out step) —
    # the 8-device cases skip there instead of erroring.
    if jax.local_device_count() < 8:
        pytest.skip("needs 8 devices")
    return make_mesh(8, axes=("nodes",))


@pytest.fixture(scope="module")
def mesh42():
    if jax.local_device_count() < 8:
        pytest.skip("needs 8 devices")
    return make_mesh(8, axes=("nodes", "batch"))


def test_make_mesh_shapes(mesh8, mesh42):
    assert mesh8.shape == {"nodes": 8}
    assert mesh42.shape == {"nodes": 4, "batch": 2}
    with pytest.raises(RuntimeError):
        make_mesh(64)


def test_group_totals_matches_replicated(mesh8, rng):
    n = 16  # 2 nodes per device
    mask = (rng.uniform(size=(n, n)) > 0.5).astype(np.float32)
    vals = rng.normal(size=n).astype(np.float32)
    got = collectives.group_totals(mesh8, jnp.asarray(mask), jnp.asarray(vals))
    want = mask @ vals
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6)


def test_alive_argmax_matches_replicated(mesh8, rng):
    n = 24
    score = rng.normal(size=n).astype(np.float32)
    alive = (rng.uniform(size=n) > 0.3).astype(np.float32)
    winner, best = collectives.alive_argmax(mesh8, jnp.asarray(score), jnp.asarray(alive))
    masked = np.where(alive > 0, score, -np.inf)
    assert int(winner) == int(np.argmax(masked))
    assert float(best) == pytest.approx(float(np.max(masked)))
    # Cross-shard ties resolve to the lowest index, like replicated argmax.
    w_tie, _ = collectives.alive_argmax(mesh8, jnp.zeros(16), jnp.ones(16))
    assert int(w_tie) == 0
    # All-dead fleets report -1, not a phantom winner.
    w_dead, _ = collectives.alive_argmax(mesh8, jnp.zeros(16), jnp.zeros(16))
    assert int(w_dead) == -1


def test_superstep_sharded_matches_unsharded(mesh42):
    feeder = cases.vvc_9bus()
    step, shard_state = make_superstep(mesh42, feeder, migration_step=1.0)

    n, b = 8, 4
    rng = np.random.default_rng(0)
    netgen = rng.normal(0, 5, n)
    scales = np.linspace(0.8, 1.2, b)
    state = shard_state(netgen, np.zeros(n), scales)

    out = step(state)
    jax.block_until_ready(out.state.gateway)

    # LB agrees with the replicated kernel.
    ref = lb.lb_round(
        jnp.asarray(netgen, jnp.float32),
        jnp.zeros(n, jnp.float32),
        jnp.ones((n, n)),
        1.0,
    )
    np.testing.assert_allclose(
        np.asarray(out.lb_out.gateway), np.asarray(ref.gateway), atol=1e-5
    )
    # GM agrees.
    g = gm.form_groups(jnp.ones(n), jnp.ones((n, n)))
    np.testing.assert_array_equal(
        np.asarray(out.group.coordinator), np.asarray(g.coordinator)
    )
    # VVC descended in every scenario lane.
    assert out.vvc_loss.shape == (b,)
    assert bool(jnp.all(jnp.isfinite(out.vvc_loss)))

    # Iterating the state converges LB (supersteps compose).
    st = out.state
    for _ in range(30):
        o = step(st)
        st = o.state
    assert int(o.lb_out.n_migrations) == 0


def test_superstep_outputs_are_sharded(mesh42):
    feeder = cases.vvc_9bus()
    step, shard_state = make_superstep(mesh42, feeder)
    state = shard_state(np.zeros(8), np.zeros(8), np.ones(2))
    out = step(state)
    # Per-node arrays land with a nodes-axis sharding.
    shard = out.lb_out.gateway.sharding
    assert shard.spec == node_sharding(mesh42, 1).spec
    # 4 distinct row-blocks over the nodes axis (replicated over batch).
    # (repr: tuple-of-slices indices are unhashable before py3.12)
    slices = {repr(s.index) for s in out.state.gateway.addressable_shards}
    assert len(slices) == 4


def test_krylov_lanes_shard_over_mesh(mesh8):
    """The scale-out recipe of pf/newton.py's memory plan, executed:
    shard the BATCH axis of lane-batched matrix-free solves over the
    mesh (each lane's inner solve stays chip-local; no cross-lane
    collectives), and match the unsharded result."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from freedm_tpu.grid.cases import synthetic_mesh
    from freedm_tpu.pf.krylov import make_krylov_solver

    sys_ = synthetic_mesh(80, seed=4, load_mw=2.0, chord_frac=1.0)
    _, solve_fixed = make_krylov_solver(sys_, max_iter=6, inner_iters=12)
    lanes = 16
    rng = np.random.default_rng(0)
    scale = rng.uniform(0.9, 1.1, (lanes, 1))
    p = jnp.asarray(scale * sys_.p_inj[None, :])
    q = jnp.asarray(scale * sys_.q_inj[None, :])

    lane_sharding = NamedSharding(mesh8, P("nodes"))
    p_sh = jax.device_put(p, lane_sharding)
    q_sh = jax.device_put(q, lane_sharding)
    batched = jax.jit(
        jax.vmap(lambda pi, qi: solve_fixed(p_inj=pi, q_inj=qi)),
        in_shardings=(lane_sharding, lane_sharding),
    )
    r_sh = batched(p_sh, q_sh)
    assert bool(jnp.all(r_sh.converged))
    # The lane axis really is distributed 2-per-device.
    assert len(r_sh.v.sharding.device_set) == 8

    r_rep = jax.jit(jax.vmap(lambda pi, qi: solve_fixed(p_inj=pi, q_inj=qi)))(p, q)
    np.testing.assert_allclose(
        np.asarray(r_sh.v), np.asarray(r_rep.v), atol=1e-10
    )


# ---------------------------------------------------------------------------
# mesh construction validation + lane-sharding helpers (ISSUE 6)
# ---------------------------------------------------------------------------


def test_make_mesh_explicit_shape_mismatch_is_typed():
    from freedm_tpu.parallel.mesh import make_mesh as mk

    # Wrong product: the error carries the device/axes arithmetic.
    with pytest.raises(ValueError, match=r"3 x 2 = 6 devices but 8"):
        mk(8, axes=("nodes", "batch"), shape=(3, 2))
    # Rank mismatch: one extent per axis.
    with pytest.raises(ValueError, match="2 dim\\(s\\) but axes"):
        mk(8, axes=("nodes",), shape=(4, 2))
    with pytest.raises(ValueError, match="every extent must be >= 1"):
        mk(8, axes=("nodes", "batch"), shape=(8, 0))
    # >2 axes without a shape cannot be inferred.
    with pytest.raises(ValueError, match="explicit shape"):
        mk(8, axes=("a", "b", "c"))
    # Shape arithmetic is validated before device availability, so the
    # typed errors above fire even on hosts with fewer than 8 devices;
    # asking for more devices than exist (with a consistent shape) is
    # still the RuntimeError.
    n_local = jax.local_device_count()
    with pytest.raises(RuntimeError, match="need"):
        mk(2 * n_local, axes=("nodes",))
    # A valid explicit shape still builds.
    m = mk(n_local, axes=("nodes", "batch"), shape=(1, n_local))
    assert m.shape == {"nodes": 1, "batch": n_local}


def test_lane_helpers(mesh8, mesh42):
    from jax.sharding import PartitionSpec as P

    from freedm_tpu.parallel.mesh import (
        lane_shards,
        lane_spec,
        validate_lane_count,
    )

    assert lane_spec(mesh8, 2) == P("nodes", None)
    assert lane_spec(mesh8, 3, lane_axis=1) == P(None, "nodes", None)
    # A two-axis mesh flattens both axes onto the lane axis by default.
    assert lane_spec(mesh42, 1) == P(("nodes", "batch"))
    assert lane_shards(mesh8) == 8
    assert lane_shards(mesh42) == 8
    assert lane_shards(mesh42, batch_spec="batch") == 2
    validate_lane_count(mesh8, 16)
    with pytest.raises(ValueError, match="does not divide"):
        validate_lane_count(mesh8, 12)
    with pytest.raises(ValueError, match="not in mesh axes"):
        lane_spec(mesh8, 1, batch_spec="bogus")


def test_resolve_device_count_and_solver_mesh():
    from freedm_tpu.parallel.mesh import resolve_device_count, solver_mesh

    local = jax.local_device_count()
    assert resolve_device_count(-1) == local
    assert resolve_device_count(0) == 1
    assert resolve_device_count(1) == 1
    with pytest.raises(ValueError, match="local device"):
        resolve_device_count(local + 1)
    assert solver_mesh(0) is None
    assert solver_mesh(1) is None
    m = solver_mesh(-1, "lanes")
    if local > 1:
        assert m.shape == {"lanes": local}
    else:
        assert m is None


def test_shard_and_gather_fns_roundtrip(mesh8):
    from jax.sharding import PartitionSpec as P

    from freedm_tpu.parallel.mesh import make_shard_and_gather_fns

    shard, gather = make_shard_and_gather_fns(
        mesh8, ({"a": P("nodes"), "b": None},)
    )
    tree = ({"a": np.arange(16.0), "b": np.float32(3.5)},)
    placed = shard(tree)
    assert len(placed[0]["a"].sharding.device_set) == 8
    assert len(placed[0]["b"].sharding.device_set) == 8  # replicated
    back = gather(placed)
    assert isinstance(back[0]["a"], np.ndarray)
    np.testing.assert_array_equal(back[0]["a"], tree[0]["a"])
    assert float(back[0]["b"]) == 3.5
