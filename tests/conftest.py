"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding paths
(pjit/shard_map over a ``jax.sharding.Mesh``) are exercised without TPU
hardware; numerics tests enable x64 to compare against the reference's
double-precision Armadillo kernels.
"""

import os

# NOTE: this environment's sitecustomize imports jax at interpreter startup
# (to register the TPU plugin), so env vars alone are read too late — the
# platform must be forced through jax.config as well.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs[:8]
