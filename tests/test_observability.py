"""Operator observability tests (VERDICT r3 item 10).

SystemState()/LoadTable()-style tables (``GroupManagement.cpp:341-414``,
``LoadBalance.cpp:454-534``) and the Logger-device ``groupStatus``
bitfield export to the plant (``docs/modules/group_management.rst:31-38``).
"""

import socket

import numpy as np
import pytest

from freedm_tpu.devices.adapters.fake import FakeAdapter
from freedm_tpu.devices.manager import DeviceManager
from freedm_tpu.runtime import Fleet, NodeHandle, build_broker
from freedm_tpu.runtime.fleet import group_status_float


def two_node_fleet_with_logger():
    managers = []
    fakes = []
    for seeds in (
        {("SST", "gateway"): 0.0, ("DRER", "generation"): 30.0,
         ("LOAD", "drain"): 10.0, ("LOG", "dgiEnable"): 1.0},
        {("SST2", "gateway"): 0.0, ("LOAD2", "drain"): 20.0},
    ):
        fake = FakeAdapter(seeds)
        m = DeviceManager()
        for (dev, _sig) in seeds:
            tname = {"SST": "Sst", "SST2": "Sst", "DRER": "Drer",
                     "LOAD": "Load", "LOAD2": "Load", "LOG": "Logger"}[dev]
            if dev not in [d for d in m.device_names()]:
                try:
                    m.add_device(dev, tname, fake)
                except ValueError:
                    pass
        fake.reveal_devices()
        managers.append(m)
        fakes.append(fake)
    fleet = Fleet(
        [NodeHandle(f"host{i}:5187{i}", m) for i, m in enumerate(managers)],
        migration_step=1.0,
    )
    return fleet, fakes


def test_group_status_bitfield_written_to_logger_device():
    fleet, fakes = two_node_fleet_with_logger()
    broker = build_broker(fleet)
    broker.run(n_rounds=3)
    group = broker.shared["group"]
    raw = fakes[0].get_state("LOG", "groupStatus")
    field = int(raw)  # integer-valued float encoding (decode: int())
    # Both nodes form one group: bits 1 and 2 (values 2, 4) are set;
    # bit 0 reflects whether node 0 coordinates.
    assert field & 2, f"self-up bit missing: {field:b}"
    assert field & 4, f"peer-up bit missing: {field:b}"
    assert bool(field & 1) == bool(group.is_coordinator[0])
    # And the helper agrees with what landed on the device.
    assert raw == pytest.approx(group_status_float(0, group))


def test_system_state_table_renders():
    fleet, fakes = two_node_fleet_with_logger()
    broker = build_broker(fleet)
    broker.run(n_rounds=2)
    table = broker._by_name["gm"].module.system_state()
    assert "- SYSTEM STATE" in table
    assert "host0:51870" in table and "host1:51871" in table
    assert "Up (Coordinator)" in table
    assert "Groups: 1" in table
    # A dead node shows Down after the next round.
    fleet.set_alive(1, False)
    broker.run(n_rounds=1)
    table = broker._by_name["gm"].module.system_state()
    assert "host1:51871 State: Down" in table


def test_load_table_renders():
    fleet, fakes = two_node_fleet_with_logger()
    broker = build_broker(fleet)
    broker.run(n_rounds=2)
    table = broker._by_name["lb"].module.load_table()
    assert "LOAD TABLE" in table
    assert "Net DRER (01):  30.00" in table
    assert "Net Load (02):  30.00" in table
    # Node roles present with gateway / netgen / predicted K columns.
    assert "(SUPPLY) host0:51870" in table
    assert "(DEMAND) host1:51871" in table
    assert "K " in table


def test_plantserver_exposes_group_bitfield_over_wire():
    """The bitfield written to a Logger device crosses the RTDS wire
    into the plant and reads back from the served state table."""
    from freedm_tpu.devices.adapters.rtds import WIRE_DTYPE, read_exactly
    from freedm_tpu.grid import cases
    from freedm_tpu.sim.plantserver import PlantServer
    from freedm_tpu.devices.adapters.plant import PlantAdapter

    plant = PlantAdapter(cases.vvc_9bus(), {"LOGGER": ("Logger", 0)})
    plant.reveal_devices()
    server = PlantServer(plant, period_s=0.01)
    addr = server.add_port(
        states=[("LOGGER", "dgiEnable"), ("LOGGER", "groupStatus")],
        commands=[("LOGGER", "groupStatus")],
    )
    server.start()
    try:
        bitfield = float(0b111)  # integer-valued float encoding
        with socket.create_connection(addr, timeout=5) as s:
            s.sendall(np.asarray([bitfield], WIRE_DTYPE).tobytes())
            raw = read_exactly(s, 2 * 4)
        states = np.frombuffer(raw, WIRE_DTYPE)
        assert states[0] == 1.0  # dgiEnable
        assert int(states[1]) == 0b111
    finally:
        server.stop()
