"""CLI + VVC-in-the-loop tests.

The flagship end-to-end scenario (VERDICT r2 item 1): a full
GM→SC→LB→VVC fleet launched by ``python -m freedm_tpu`` from config
files alone (freedm.cfg + timings.cfg + device.xml + adapter.xml +
topology.cfg), running against a *separate-process* plant server over
real TCP sockets, with VVC losses decreasing — the reference's
PosixBroker + pscad-interface deployment
(``Broker/src/PosixMain.cpp:113-442``).
"""

import dataclasses
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from freedm_tpu.core.config import NULL_COMMAND, GlobalConfig, Timings
from freedm_tpu.devices.adapters.plant import PlantAdapter
from freedm_tpu.devices.manager import DeviceManager
from freedm_tpu.grid import cases
from freedm_tpu.runtime import Fleet, NodeHandle, VvcModule, build_broker

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# ---------------------------------------------------------------------------
# In-process: VVC in the round loop, closed through plant physics
# ---------------------------------------------------------------------------


def build_vvc_plant_fleet():
    """Single-node fleet with per-phase Pload/Sst devices on every
    feeder row, physics grounded in the feeder's own spot loads so the
    controller's model descent is the plant's real descent."""
    feeder = cases.vvc_9bus()
    placements = {"SST1": ("Sst", 2), "OMEGA": ("Omega", 0)}
    for row in range(feeder.n_branches):
        for ph in "abc":
            placements[f"Pl{row}_{ph}"] = (f"Pload_{ph}", row)
            placements[f"Q{row}_{ph}"] = (f"Sst_{ph}", row)
    plant = PlantAdapter(feeder, placements, feeder_base_load=True)
    manager = DeviceManager(capacity=64)
    for name, (tname, _) in placements.items():
        manager.add_device(name, tname, plant)
    plant.reveal_devices()
    plant.start()
    fleet = Fleet([NodeHandle("n0:50850", manager)])
    fleet.plants.append(plant)
    return fleet, plant, feeder


def test_vvc_module_reduces_plant_losses():
    fleet, plant, feeder = build_vvc_plant_fleet()
    loss_initial = plant.loss_kw
    vvc = VvcModule(fleet, feeder)
    broker = build_broker(fleet, extra_modules=[vvc])
    broker.run(n_rounds=8)
    out = broker.shared["vvc"]
    # The accepted model loss and the plant's actual loss agree (same
    # base case) and both dropped below the uncontrolled loss.
    assert float(out.loss_after_kw) < loss_initial - 0.01
    assert plant.loss_kw < loss_initial - 0.01
    assert plant.loss_kw == pytest.approx(float(out.loss_after_kw), abs=0.05)
    assert vvc.improved_rounds >= 1
    # Setpoints actually flowed to the plant as per-phase Sst commands.
    assert np.abs(plant._q_inj_kvar).sum() > 0.0
    # Rows 0..7 all carry Pload devices with live (=default) readings:
    # every read hits the staleness sentinel, reference-style.
    assert vvc.stale_reads > 0


def test_vvc_module_respects_device_mask():
    fleet, plant, feeder = build_vvc_plant_fleet()
    # Drop all but row 4's Q devices: the control mask must shrink to
    # exactly that row's phases.
    manager = fleet.nodes[0].manager
    for row in range(feeder.n_branches):
        if row != 4:
            for ph in "abc":
                manager.remove_device(f"Q{row}_{ph}")
    vvc = VvcModule(fleet, feeder)
    broker = build_broker(fleet, extra_modules=[vvc])
    broker.run(n_rounds=4)
    q = np.asarray(vvc.q_kvar)
    mask = np.zeros_like(q)
    mask[4, :] = 1.0
    assert np.all(q * (1 - mask) == 0.0)
    assert np.abs(q[4]).sum() > 0.0  # the controlled row moved


# ---------------------------------------------------------------------------
# Config-file generation for the CLI e2e
# ---------------------------------------------------------------------------

# (name, type, node, seed value or None) — LB story matches the 3-node
# demo fixture; Pload/Q rows exercise the VVC read/scatter paths.
RIG_DEVICES = (
    [
        ("SST1", "Sst", 2, None), ("DRER_A", "Drer", 1, 30.0),
        ("LOAD_A", "Load", 0, 10.0), ("OMEGA", "Omega", 0, None),
        ("SST2", "Sst", 4, None), ("LOAD_B", "Load", 5, 30.0),
        ("DRER_B", "Drer", 6, 10.0),
        ("SST3", "Sst", 7, None), ("LOAD_C", "Load", 3, 20.0),
        ("DRER_C", "Drer", 3, 20.0),
    ]
    + [(f"Pl{row}_{ph}", f"Pload_{ph}", row, None)
       for row in (0, 3, 5) for ph in "abc"]
    + [(f"Q{row}_{ph}", f"Sst_{ph}", row, None)
       for row in (2, 4, 6, 7) for ph in "abc"]
)

# Per-DGI-node adapter tables: device -> list of (device, signal) states
# and commands, in buffer-index order (shared by rig.xml and adapter.xml).
NODE_TABLES = {
    "node0:50810": {
        "states": [("SST1", "gateway"), ("DRER_A", "generation"),
                   ("LOAD_A", "drain"), ("OMEGA", "frequency")]
        + [(f"Pl{row}_{ph}", "pload") for row in (0, 3, 5) for ph in "abc"]
        + [(f"Q{row}_{ph}", "gateway") for row in (2, 4, 6, 7) for ph in "abc"],
        "commands": [("SST1", "gateway")]
        + [(f"Q{row}_{ph}", "gateway") for row in (2, 4, 6, 7) for ph in "abc"],
    },
    "node1:50811": {
        "states": [("SST2", "gateway"), ("LOAD_B", "drain"),
                   ("DRER_B", "generation")],
        "commands": [("SST2", "gateway")],
    },
    "node2:50812": {
        "states": [("SST3", "gateway"), ("LOAD_C", "drain"),
                   ("DRER_C", "generation")],
        "commands": [("SST3", "gateway")],
    },
}

TYPE_OF = {name: tname for name, tname, _, _ in RIG_DEVICES}


def write_rig_xml(path):
    lines = ['<rig case="vvc_9bus" base="feeder" period="0.02">']
    for name, tname, node, value in RIG_DEVICES:
        v = f' value="{value}"' if value is not None else ""
        lines.append(f'  <device name="{name}" type="{tname}" node="{node}"{v}/>')
    for uuid in NODE_TABLES:
        lines.append('  <adapter port="0">')
        for kind in ("state", "command"):
            for i, (dev, sig) in enumerate(NODE_TABLES[uuid][kind + "s"]):
                lines.append(f'    <{kind} device="{dev}" signal="{sig}" index="{i}"/>')
        lines.append("  </adapter>")
    lines.append("</rig>")
    path.write_text("\n".join(lines))


def write_adapter_xml(path, ports):
    lines = ["<root>"]
    for (uuid, tables), port in zip(NODE_TABLES.items(), ports):
        owner = "" if uuid == "node0:50810" else f' owner="{uuid}"'
        lines.append(f'  <adapter name="sim-{uuid.split(":")[0]}" type="rtds"{owner}>')
        lines.append(f"    <info><host>127.0.0.1</host><port>{port}</port>"
                     f"<poll>0.02</poll></info>")
        for kind in ("state", "command"):
            lines.append(f"    <{kind}>")
            for i, (dev, sig) in enumerate(tables[kind + "s"]):
                lines.append(
                    f'      <entry index="{i + 1}"><type>{TYPE_OF[dev]}</type>'
                    f"<device>{dev}</device><signal>{sig}</signal></entry>"
                )
            lines.append(f"    </{kind}>")
        lines.append("  </adapter>")
    lines.append("</root>")
    path.write_text("\n".join(lines))


def write_device_xml(path):
    from freedm_tpu.devices.schema import DEFAULT_TYPES

    lines = ["<root>"]
    for t in DEFAULT_TYPES:
        lines.append(f"  <deviceType><id>{t.id}</id>")
        for s in t.states:
            lines.append(f"    <state>{s}</state>")
        for c in t.commands:
            lines.append(f"    <command>{c}</command>")
        lines.append("  </deviceType>")
    lines.append("</root>")
    path.write_text("\n".join(lines))


def write_configs(tmp_path, ports):
    write_adapter_xml(tmp_path / "adapter.xml", ports)
    write_device_xml(tmp_path / "device.xml")
    (tmp_path / "timings.cfg").write_text(
        "\n".join(
            f"{f.name.upper()} = {getattr(Timings(), f.name)}"
            for f in dataclasses.fields(Timings)
        )
    )
    (tmp_path / "topology.cfg").write_text(
        "edge v0 v1\nedge v1 v2\n"
        "sst v0 node0:50810\nsst v1 node1:50811\nsst v2 node2:50812\n"
    )
    (tmp_path / "freedm.cfg").write_text(
        "hostname = node0\nport = 50810\n"
        "add-host = node1:50811\nadd-host = node2:50812\n"
        "vvc-case = vvc_9bus\nmigration-step = 1\n"
        f"device-config = {tmp_path}/device.xml\n"
        f"adapter-config = {tmp_path}/adapter.xml\n"
        f"timings-config = {tmp_path}/timings.cfg\n"
        f"topology-config = {tmp_path}/topology.cfg\n"
    )


# ---------------------------------------------------------------------------
# The e2e itself
# ---------------------------------------------------------------------------


def _sub_env():
    return dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")


@pytest.fixture
def plant_rig(tmp_path):
    write_rig_xml(tmp_path / "rig.xml")
    proc = subprocess.Popen(
        [sys.executable, "-m", "freedm_tpu.sim.plantserver", str(tmp_path / "rig.xml")],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=_sub_env(), text=True,
    )
    line = proc.stdout.readline()
    try:
        ports = [p for _, p in json.loads(line)["plantserver"]]
    except Exception:
        proc.terminate()
        raise RuntimeError(
            f"plantserver failed: {line!r} {proc.stderr.read()[:2000]}"
        )
    yield ports
    proc.terminate()
    proc.wait(timeout=5)


def test_cli_full_round_from_config_files(tmp_path, plant_rig):
    write_configs(tmp_path, plant_rig)
    out = subprocess.run(
        [sys.executable, "-m", "freedm_tpu", "-c", str(tmp_path / "freedm.cfg"),
         "--rounds", "12", "--summary-every", "1"],
        capture_output=True, env=_sub_env(), text=True, timeout=600,
    )
    assert out.returncode == 0, out.stderr[-3000:]
    lines = [json.loads(l) for l in out.stdout.splitlines() if l.startswith("{")]
    assert len(lines) == 12
    # One 3-node group formed (topology.cfg honored, all nodes alive).
    assert lines[-1]["n_groups"] == 1
    # LB migrated power (supply node0 has +20 kW surplus).
    assert sum(l["migrations"] for l in lines) > 0
    # VVC: rounds before the RTDS reveal are skipped (no actuation);
    # once devices appear, losses decrease and stay non-increasing.
    losses = [l["vvc_loss_kw"] for l in lines if "vvc_loss_kw" in l]
    assert len(losses) >= 8, lines
    tail = losses[3:]
    assert all(b <= a + 1e-9 for a, b in zip(tail, tail[1:])), losses
    assert tail[-1] < losses[0], losses
    assert any(l.get("vvc_improved") for l in lines)

    # The accepted Q setpoints crossed the wire: read the plant's state
    # table back through node0's port and check the Q rows moved.
    import socket

    from freedm_tpu.devices.adapters.rtds import WIRE_DTYPE, read_exactly

    tables = NODE_TABLES["node0:50810"]
    with socket.create_connection(("127.0.0.1", plant_rig[0]), timeout=5) as s:
        cmds = np.full(len(tables["commands"]), NULL_COMMAND, WIRE_DTYPE)
        s.sendall(cmds.tobytes())
        raw = read_exactly(s, 4 * len(tables["states"]))
    states = np.frombuffer(raw, WIRE_DTYPE).astype(np.float64)
    q_states = states[-12:]  # the Q{row}_{ph} gateway entries
    assert np.abs(q_states).sum() > 0.0


def test_cli_uuid_and_list_loggers(tmp_path):
    out = subprocess.run(
        [sys.executable, "-m", "freedm_tpu", "-u", "-p", "1870"],
        capture_output=True, env=_sub_env(), text=True, timeout=120,
    )
    assert out.returncode == 0 and out.stdout.strip() == "localhost:1870"
    out = subprocess.run(
        [sys.executable, "-m", "freedm_tpu", "--list-loggers"],
        capture_output=True, env=_sub_env(), text=True, timeout=120,
    )
    assert out.returncode == 0


def test_vvc_row_of_override_is_range_checked():
    fleet, plant, feeder = build_vvc_plant_fleet()
    vvc = VvcModule(fleet, feeder, row_of={"Q2_a": -1})
    with pytest.raises(ValueError, match="outside feeder"):
        vvc._row("Q2_a")


def test_vvc_row_ignores_pnp_namespace_digits():
    # PnP devices are namespaced "ident:name"; a digit in the controller
    # ident must not pick the branch row.
    fleet, plant, feeder = build_vvc_plant_fleet()
    vvc = VvcModule(fleet, feeder)
    assert vvc._row("Q5_a") == 5
    assert vvc._row("ctrl1:Q5_a") == 5
    with pytest.raises(ValueError, match="no integer"):
        vvc._row("ctrl1:Qx_a")


def test_vvc_staleness_is_exact_f4_sentinel():
    """A never-updated signal reads the f4 round-trip of the default →
    stale; a plant legitimately at the full-precision default is used
    (reference exact-compare, vvc/VoltVarCtrl.cpp:443-520)."""
    fleet, plant, feeder = build_vvc_plant_fleet()
    vvc = VvcModule(fleet, feeder)
    default = float(np.asarray(feeder.s_load.real)[2, 0])  # -33.333... not f4-exact
    assert default != float(np.float32(default))

    readings = {}

    def fake_get_state(name, sig):
        return readings.get((name, sig), plant.get_state(name, sig))

    manager = fleet.nodes[0].manager
    # Wire-f4 round-trip of the default → stale (kept at default).
    readings[("Pl2_a", "pload")] = float(np.float32(default))
    # The exact float64 default → live, used as-is.
    readings[("Pl2_b", "pload")] = default
    orig = manager.get_state
    manager.get_state = fake_get_state
    try:
        broker = build_broker(fleet, extra_modules=[vvc])
        before = vvc.stale_reads
        broker.run(n_rounds=1)
    finally:
        manager.get_state = orig
    # Row 2 phase a counted stale; phase b (exact default) did not.
    # Rows 1/4/7 have integer (f4-exact) defaults: those reads are
    # indistinguishable from unset buffers and count stale, like the
    # reference's "Pl1_a && xx == 80".
    stale = vvc.stale_reads - before
    n_f4_exact_rows = 4  # rows 0 (zero), 1, 4, 7 × 3 phases
    assert stale == n_f4_exact_rows * 3 + 1


def test_vvc_skips_rounds_without_actuation():
    # All Sst_x devices gone: VVC must skip (publishing a model-only
    # descent would claim control the plant never receives).
    fleet, plant, feeder = build_vvc_plant_fleet()
    manager = fleet.nodes[0].manager
    for row in range(feeder.n_branches):
        for ph in "abc":
            manager.remove_device(f"Q{row}_{ph}")
    vvc = VvcModule(fleet, feeder)
    broker = build_broker(fleet, extra_modules=[vvc])
    broker.run(n_rounds=3)
    assert vvc.skipped_rounds == 3
    assert "vvc" not in broker.shared


def test_plant_pload_command_sets_phase_load():
    fleet, plant, feeder = build_vvc_plant_fleet()
    manager = fleet.nodes[0].manager
    before = manager.get_state("Pl2_a", "pload")
    manager.set_command("Pl2_a", "pload", before + 7.5)
    assert manager.get_state("Pl2_a", "pload") == pytest.approx(before + 7.5)


def test_plant_pload_command_does_not_mutate_feeder():
    # _s_base must be the plant's own copy: the feeder object is shared
    # with the VVC controller model, whose base case and staleness
    # sentinel must not drift when the plant's load is commanded.
    fleet, plant, feeder = build_vvc_plant_fleet()
    before = np.array(feeder.s_load)
    fleet.nodes[0].manager.set_command("Pl2_a", "pload", 999.0)
    assert np.array_equal(np.asarray(feeder.s_load), before)


def test_build_runtime_rejects_unknown_owner(tmp_path):
    write_device_xml(tmp_path / "device.xml")
    (tmp_path / "adapter.xml").write_text(
        '<root><adapter name="x" type="fake" owner="ghost:1">'
        "<state><entry index=\"1\"><type>Sst</type><device>S</device>"
        "<signal>gateway</signal></entry></state></adapter></root>"
    )
    from freedm_tpu.cli import build_runtime

    cfg = GlobalConfig(
        hostname="node0", port=50810,
        device_config=str(tmp_path / "device.xml"),
        adapter_config=str(tmp_path / "adapter.xml"),
    )
    with pytest.raises(ValueError, match="owner"):
        build_runtime(cfg)
