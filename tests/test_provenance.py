"""Provenance receipts + shadow verification (``core/provenance.py``,
ISSUE 17): rate-spec grammar, same-seed sampler determinism (per-tier
independent streams), byte-stable receipt JSONL, per-tier receipt
shapes off a live serve ladder, the disabled-by-default tripwire, the
``GET /provenance`` route schema, and the ``tools/audit_report.py``
receipts x traces x events join.  The *negative* proof — an injected
cache corruption the shadow verifier must catch — lives in
``tools/chaos.py --shadow-negative`` (CI runs it); these tests pin the
machinery that proof rides on.
"""

import json
import types
import urllib.request

import numpy as np
import pytest

from freedm_tpu.core.provenance import (
    PROVENANCE,
    RECEIPT_FIELDS,
    TIERS,
    _Sampler,
    parse_rate_spec,
)
from freedm_tpu.serve import ServeConfig, ServeServer, Service
from freedm_tpu.serve.service import PowerFlowRequest
from freedm_tpu.tools import audit_report

BUCKETS = (1, 2, 4)
T = 300  # first touches compile


@pytest.fixture(scope="module")
def svc():
    PROVENANCE.configure(enabled=True, rate_spec="0.0",
                         replica="prov-test")
    s = Service(ServeConfig(max_batch=4, max_wait_ms=5.0, queue_depth=64,
                            buckets=BUCKETS))
    r = s.request("pf", PowerFlowRequest(case="case14", timeout_s=T))
    assert r.converged and r.batch.tier == "full"
    s._prime_receipt = r.provenance  # the full-tier receipt, stashed
    yield s
    s.stop()
    PROVENANCE.reset()


def _base_inj(svc):
    eng = svc.engine("pf", "case14")
    return np.array(eng._p0), np.array(eng._q0)


# ---------------------------------------------------------------------------
# rate-spec grammar + sampler determinism
# ---------------------------------------------------------------------------


def test_rate_spec_grammar():
    assert parse_rate_spec("") == (None, {"default": 0.0})
    assert parse_rate_spec("0.05") == (None, {"default": 0.05})
    seed, rates = parse_rate_spec("seed=7;0.01,exact=1.0,delta=0.5")
    assert seed == 7
    assert rates == {"default": 0.01, "exact": 1.0, "delta": 0.5}
    # Rates clamp to [0, 1]; a typo'd tier is a typed error, not a
    # silently-sampling-nothing config.
    assert parse_rate_spec("exact=7")[1]["exact"] == 1.0
    with pytest.raises(ValueError, match="unknown shadow-verify tier"):
        parse_rate_spec("exatc=1.0")
    with pytest.raises(ValueError, match="bad shadow-verify rate"):
        parse_rate_spec("exact=lots")
    with pytest.raises(ValueError, match="bad shadow-verify seed"):
        parse_rate_spec("seed=x;0.5")


def test_same_seed_sampler_picks_identical_indices():
    a = _Sampler(7, {"default": 0.3})
    b = _Sampler(7, {"default": 0.3})
    draws_a = [a.should("exact") for _ in range(200)]
    draws_b = [b.should("exact") for _ in range(200)]
    assert draws_a == draws_b
    assert any(draws_a) and not all(draws_a)  # actually probabilistic
    # Per-tier streams are independent: interleaving another tier's
    # draws must not perturb this tier's sequence (the faults.py
    # discipline — a replayed load samples the same answers per tier).
    c = _Sampler(7, {"default": 0.3})
    draws_c = []
    for _ in range(200):
        c.should("delta")
        draws_c.append(c.should("exact"))
    assert draws_c == draws_a
    # Boundary rates short-circuit without consuming stream state.
    z = _Sampler(0, {"default": 0.0, "exact": 1.0})
    assert all(z.should("exact") for _ in range(10))
    assert not any(z.should("warm") for _ in range(10))


# ---------------------------------------------------------------------------
# receipt byte-stability
# ---------------------------------------------------------------------------


def test_receipt_log_json_is_byte_stable_and_schema_ordered():
    span = types.SimpleNamespace(trace_id="cafe0123")
    kw = dict(workload="pf", case="case14", tier="delta", span=span,
              backend="dense", precision="mixed", fallbacks=2,
              iterations=3, residual=1.25e-7, warm_source=None,
              cache_age_s=0.5)
    r1 = PROVENANCE.stamp(types.SimpleNamespace(), **kw)
    r2 = PROVENANCE.stamp(types.SimpleNamespace(), **kw)
    line1 = PROVENANCE.receipt_log_json(r1)
    line2 = PROVENANCE.receipt_log_json(r2)
    assert line1 == line2  # same inputs -> byte-identical JSONL
    # Emission order is the schema order — the contract that makes a
    # receipt diffable across runs and joinable by column tools.
    assert list(json.loads(line1).keys()) == list(RECEIPT_FIELDS)
    assert list(r1.keys()) == list(RECEIPT_FIELDS)


# ---------------------------------------------------------------------------
# per-tier receipt shapes off the live ladder
# ---------------------------------------------------------------------------


def test_full_tier_receipt_shape(svc):
    r = svc._prime_receipt
    assert r is not None and list(r.keys()) == list(RECEIPT_FIELDS)
    assert r["tier"] == "full" and r["workload"] == "pf"
    assert r["case"] == "case14" and r["replica"] == "prov-test"
    assert r["pf_backend"] in ("dense", "sparse")
    assert r["pf_precision"] in ("f64", "mixed")
    assert isinstance(r["iterations"], int) and r["iterations"] >= 1
    assert r["bucket"] in BUCKETS and r["lanes"] >= 1
    assert r["solve_ms"] > 0.0


def test_exact_tier_receipt_shape(svc):
    r = svc.request("pf", PowerFlowRequest(case="case14", timeout_s=T))
    assert r.batch.tier == "exact"
    rec = r.provenance
    assert list(rec.keys()) == list(RECEIPT_FIELDS)
    assert rec["tier"] == "exact"
    assert rec["cache_age_s"] is not None and rec["cache_age_s"] >= 0.0
    assert rec["bucket"] == 0 and rec["solve_ms"] == 0.0
    assert rec["trace_id"] is None  # tracing off -> honest null, not ""


def test_delta_tier_receipt_carries_measured_residual(svc):
    p0, q0 = _base_inj(svc)
    p = p0.copy()
    p[4] += 0.03  # rank-1, small magnitude: the delta tier's home turf
    r = svc.request("pf", PowerFlowRequest(
        case="case14", p_inj=p.tolist(), q_inj=q0.tolist(), timeout_s=T))
    assert r.batch.tier == "delta"
    rec = r.provenance
    assert rec["tier"] == "delta"
    # residual_pu on a delta receipt is the host-f64 verify, not a claim.
    assert rec["residual_pu"] is not None and rec["residual_pu"] <= 1e-6
    assert rec["cache_age_s"] is not None


def test_warm_tier_receipt_names_its_source(svc):
    # One bus past the delta tier's 0.5 pu magnitude cap: too big for
    # the SMW correction, but a near entry still seeds the warm start.
    p0, q0 = _base_inj(svc)
    p = p0.copy()
    p[8] += 0.6
    r = svc.request("pf", PowerFlowRequest(
        case="case14", p_inj=p.tolist(), q_inj=q0.tolist(), timeout_s=T))
    rec = r.provenance
    assert rec["tier"] == "warm"
    assert rec["warm_source"]  # the cache-entry digest it was seeded from
    assert rec["bucket"] in BUCKETS  # warm IS a dispatched solve


# ---------------------------------------------------------------------------
# disabled-by-default tripwire
# ---------------------------------------------------------------------------


def test_disabled_mode_stamps_nothing(svc):
    # The acceptance bar: when off, serve paths pay one attribute check
    # and responses carry no provenance key at all.
    before = dict(PROVENANCE._receipts)
    PROVENANCE.enabled = False
    try:
        r = svc.request("pf", PowerFlowRequest(case="case14", timeout_s=T))
        assert r.provenance is None
        assert "provenance" not in r.to_dict()
        assert PROVENANCE._receipts == before
    finally:
        PROVENANCE.enabled = True
    # Boot state is disabled (the singleton must not leak between
    # processes that never opted in).
    assert type(PROVENANCE)().enabled is False


# ---------------------------------------------------------------------------
# GET /provenance route
# ---------------------------------------------------------------------------


def test_provenance_route_schema(svc):
    srv = ServeServer(svc, port=0).start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/provenance", timeout=10
        ) as resp:
            doc = json.loads(resp.read())
    finally:
        srv.stop()
    assert doc["enabled"] is True and doc["replica"] == "prov-test"
    assert set(doc["sampler"]) == {"seed", "rates"}
    assert doc["mismatch_tol"] == pytest.approx(1e-4)
    # Every ladder tier this module exercised shows up, counted.
    for tier in ("full", "exact", "delta", "warm"):
        assert doc["receipts"].get(tier, 0) >= 1, tier
    assert set(doc["receipts"]) <= set(TIERS)
    assert isinstance(doc["shadow"], dict)
    assert doc["shadow_queue_depth"] == 0
    # Drift windows key on case|tier|precision and summarize residuals.
    assert any(k.startswith("case14|") for k in doc["drift"])
    win = next(v for k, v in doc["drift"].items()
               if k.startswith("case14|delta|"))
    assert win["count"] >= 1 and "residual_p50" in win
    # The condensed /stats fold agrees with the full document.
    blk = svc.stats()["provenance"]
    assert blk["enabled"] is True
    assert blk["receipts"] == doc["receipts"]


# ---------------------------------------------------------------------------
# audit_report: the receipts x traces x events join
# ---------------------------------------------------------------------------


def test_audit_report_joins_streams_by_trace_id(svc, tmp_path):
    from freedm_tpu.core.tracing import TRACER

    rlog = tmp_path / "receipts.jsonl"
    tlog = tmp_path / "trace.jsonl"
    elog = tmp_path / "events.jsonl"
    TRACER.configure(enabled=True, node="prov-test", path=str(tlog))
    PROVENANCE.configure(log=str(rlog))
    p0, q0 = _base_inj(svc)
    try:
        tids = []
        for bump in (0.011, 0.012):
            p = p0.copy()
            p[6] += bump
            r = svc.request("pf", PowerFlowRequest(
                case="case14", p_inj=p.tolist(), q_inj=q0.tolist(),
                timeout_s=T))
            tids.append(r.provenance["trace_id"])
        assert all(tids) and tids[0] != tids[1]
    finally:
        # Full reset, not just disable: the flight-recorder ring would
        # otherwise leak this test's batch-less cache-tier
        # serve.request spans into later modules' tail() polls.
        TRACER.reset()
        PROVENANCE._journal.close()
    # A journal with one indicting event for the second request and one
    # event that mentions no request at all.
    elog.write_text(
        json.dumps({"event": "shadow.mismatch", "max_dv_pu": 0.05,
                    "tol": 1e-4, "receipt": {"trace_id": tids[1]}}) + "\n"
        + json.dumps({"event": "slo.breach", "objective": "x"}) + "\n"
    )

    audit = audit_report.build_audit([str(rlog)], [str(tlog)], [str(elog)])
    assert audit["receipts"] == 2
    assert audit["receipts_without_trace_id"] == 0
    assert set(audit["trails"]) == set(tids)
    assert audit["events_unjoined"] == 1
    # The flagged trail is exactly the indicted request...
    assert audit["flagged"] == [tids[1]]
    assert audit["trails"][tids[1]]["events"][0]["event"] == "shadow.mismatch"
    # ...and every trail carries its span tree, serve.request included.
    for tid in tids:
        tr = audit["trails"][tid]["trace"]
        assert tr is not None and tr["spans"] >= 1
        assert any(s["name"] == "serve.request" for s in tr["tree"])

    text = audit_report.render_text(audit)
    assert "** FLAGGED **" in text and tids[1] in text
    # The CLI doubles as a gate: flagged trails -> exit 1.
    assert audit_report.main(
        ["--receipts", str(rlog), "--trace", str(tlog),
         "--events", str(elog), "--only-flagged"]) == 1


def test_audit_report_counts_untraced_receipts(tmp_path):
    # Receipts stamped while tracing was off join nothing — counted,
    # never silently dropped.
    rlog = tmp_path / "r.jsonl"
    rec = {k: None for k in RECEIPT_FIELDS}
    rec.update(tier="exact", workload="pf", case="case14")
    rlog.write_text(json.dumps(rec) + "\n")
    audit = audit_report.build_audit([str(rlog)])
    assert audit["receipts"] == 1
    assert audit["receipts_without_trace_id"] == 1
    assert audit["trails"] == {} and audit["flagged"] == []
