"""Automatic failure detection: device health drives node liveness.

The reference closes this loop with GM's AYC/AYT timeouts → Recovery
(``gm/GroupManagement.cpp:513-552,851-893``) plus the transports'
staleness detectors (RTDS socket death, PnP heartbeat).  Here the fleet
derives each node's liveness from its device health every GM phase
(``Fleet.refresh_liveness``): killing a plant server or silencing a PnP
controller re-forms groups with **no** manual ``set_alive`` call.
"""

import time

import numpy as np

from freedm_tpu.devices.adapters.plant import PlantAdapter
from freedm_tpu.devices.adapters.pnp import PnpServer
from freedm_tpu.devices.adapters.rtds import RtdsAdapter
from freedm_tpu.devices.manager import DeviceManager
from freedm_tpu.grid import cases
from freedm_tpu.runtime import Fleet, NodeHandle, build_broker
from freedm_tpu.sim.controller import PnpClient
from freedm_tpu.sim.plantserver import PlantServer


def wait_for(cond, timeout=5.0, what="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


def make_rtds_node(gen_kw: float, load_kw: float):
    """One DGI node backed by its own plant-server over a real socket."""
    feeder = cases.vvc_9bus()
    placements = {"SST": ("Sst", 2), "GEN": ("Drer", 1), "LOAD": ("Load", 0)}
    plant = PlantAdapter(feeder, placements)
    plant.set_generation("GEN", gen_kw)
    plant.set_load("LOAD", load_kw)
    plant.reveal_devices()
    server = PlantServer(plant, period_s=0.01)
    states = [("SST", "gateway"), ("GEN", "generation"), ("LOAD", "drain")]
    host, port = server.add_port(states, [("SST", "gateway")])
    server.start()
    ad = RtdsAdapter(host, port, poll_s=0.01, socket_timeout_s=0.3)
    for i, (d, s) in enumerate(states):
        ad.bind_state(d, s, i)
    ad.bind_command("SST", "gateway", 0)
    manager = DeviceManager(capacity=8)
    for name, (tname, _) in placements.items():
        manager.add_device(name, tname, ad)
    ad.start()
    return manager, server, ad


def test_plant_server_death_regroups_fleet_automatically():
    nodes, servers, adapters = [], [], []
    try:
        for gen, load in [(30.0, 10.0), (10.0, 30.0), (20.0, 20.0)]:
            m, srv, ad = make_rtds_node(gen, load)
            nodes.append(m)
            servers.append(srv)
            adapters.append(ad)
        fleet = Fleet(
            [NodeHandle(f"n{i}:1", m) for i, m in enumerate(nodes)],
            auto_liveness=True,
        )
        broker = build_broker(fleet)
        # Wait for all adapters to reveal, then poll rounds until the
        # full 3-node group forms (a fixed round count raced the
        # adapters' first health-bearing polls: auto-liveness counts a
        # node with no fresh device data as down, so the GM phase can
        # legitimately see an empty fleet for the first few rounds).
        wait_for(lambda: all(a.revealed for a in adapters), what="reveal")

        def run_until(cond, what, max_rounds=60):
            for _ in range(max_rounds):
                broker.run(n_rounds=1)
                if cond(broker.shared["group"]):
                    return
                time.sleep(0.02)
            raise AssertionError(f"no {what} within {max_rounds} rounds")

        run_until(
            lambda g: int(g.n_groups) == 1 and int(g.group_size[0]) == 3,
            what="full 3-node group",
        )
        g = broker.shared["group"]
        assert int(g.n_groups) == 1 and int(g.group_size[0]) == 3

        # Kill node 0's plant server mid-run.  NO set_alive anywhere:
        # the dead socket errors the adapter, the next GM phase drops
        # the node, and the survivors regroup.
        servers[0].stop()
        wait_for(lambda: adapters[0].error is not None, what="adapter error")
        run_until(
            lambda g: int(g.n_groups) == 1 and int(g.coordinator[0]) == -1,
            what="2-node regroup without node 0",
        )
        g = broker.shared["group"]
        assert not fleet.nodes[0].alive
        assert int(g.n_groups) == 1
        assert int(g.coordinator[0]) == -1  # node 0 out of the group
        assert np.asarray(g.group_mask)[1, 0] == 0
    finally:
        for a in adapters:
            a.stop()
        for s in servers:
            s.stop()


def test_pnp_join_and_silence_regroup_fleet():
    """Full PnP Done-criterion: Hello-join mid-run grows the group,
    heartbeat silence shrinks it — group membership tracks the session
    with no manual liveness management."""
    from freedm_tpu.devices.adapters.fake import FakeAdapter

    fake = FakeAdapter()
    ma = DeviceManager(capacity=8)
    ma.add_device("SST_A", "Sst", fake)
    ma.add_device("GEN_A", "Drer", fake)
    fake.reveal_devices()
    fake.set_state("SST_A", "gateway", 0.0)
    fake.set_state("GEN_A", "generation", 20.0)

    mb = DeviceManager(capacity=8)
    srv = PnpServer(mb, heartbeat_s=0.4).start()
    try:
        fleet = Fleet(
            [NodeHandle("a:1", ma), NodeHandle("b:2", mb)], auto_liveness=True
        )
        broker = build_broker(fleet)
        broker.run(n_rounds=2)
        g = broker.shared["group"]
        # Node B has no devices yet: it is down, A groups alone.
        assert not fleet.nodes[1].alive
        assert int(g.group_size[0]) == 1

        c = PnpClient("ctrlB", srv.address)
        c.enable("Sst", "sst", gateway=0.0)
        c.enable("Load", "plant", drain=10.0)
        assert c.connect() == "Start"
        c.exchange()  # land the first DeviceStates before any round runs

        import threading

        pumping = threading.Event()
        pumping.set()

        def pump():
            while pumping.is_set():
                try:
                    c.exchange()
                except (ConnectionError, OSError):
                    return
                time.sleep(0.05)

        t = threading.Thread(target=pump, daemon=True)
        t.start()
        broker.run(n_rounds=3)
        g = broker.shared["group"]
        assert fleet.nodes[1].alive
        assert int(g.n_groups) == 1 and int(g.group_size[0]) == 2
        # The joined node's demand was served by LB.
        assert int(broker.shared["lb_round"].state[1]) == -1  # DEMAND

        # Silence → heartbeat reap → automatic regroup.
        pumping.clear()
        t.join(timeout=2)
        wait_for(lambda: not mb.device_names(), timeout=3.0, what="reap")
        broker.run(n_rounds=2)
        g = broker.shared["group"]
        assert not fleet.nodes[1].alive
        assert int(g.group_size[0]) == 1
        c.close()
    finally:
        srv.stop()


def test_manual_disable_overrides_auto_liveness():
    from freedm_tpu.devices.adapters.fake import FakeAdapter

    fake = FakeAdapter()
    m = DeviceManager(capacity=4)
    m.add_device("SST", "Sst", fake)
    fake.reveal_devices()
    fleet = Fleet([NodeHandle("a:1", m)], auto_liveness=True)
    fleet.refresh_liveness()
    assert fleet.nodes[0].alive
    fleet.set_alive(0, False)  # operator forces the node down
    fleet.refresh_liveness()
    assert not fleet.nodes[0].alive  # healthy devices do not resurrect it
