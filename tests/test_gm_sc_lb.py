"""Mesh-kernel tests for the three DGI algorithm modules.

Each test states the reference behavior it mirrors (file:line in
/root/reference); the kernels must reproduce the protocol *outcomes*
(group partitions, election winners, migration trajectories,
conservation invariants) without the message choreography.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from freedm_tpu.grid import topology as topo_mod
from freedm_tpu.modules import gm, lb, sc

TOPOLOGY_CFG = """
# 4-node ring with FID-controlled cross-ties (same DSL as the
# reference's topology.cfg: edge / sst / fid directives).
edge a b
edge b c
edge c d
fid d a FID_DA
fid b d FID_BD
sst a host1:50000
sst b host2:50000
sst c host3:50000
sst d host4:50000
"""


def full_mesh(n):
    return jnp.ones((n, n))


# ---------------------------------------------------------------------------
# gm: group formation + election
# ---------------------------------------------------------------------------


def test_single_group_elects_max_priority():
    # All alive, fully reachable => one group led by the max-priority
    # node (GroupManagement.cpp:710-762: highest priority coordinator).
    n = 8
    alive = jnp.ones(n)
    g = gm.form_groups(alive, full_mesh(n))
    prio = gm.node_priority(n)
    want = int(np.argmax(prio))
    assert int(g.n_groups) == 1
    assert np.all(np.asarray(g.coordinator) == want)
    assert bool(g.is_coordinator[want])
    assert np.all(np.asarray(g.group_size) == n)


def test_partition_forms_two_groups():
    # Reachability split => independent groups with their own leaders
    # (the reference's group-split-on-partition behavior).
    n = 6
    reach = np.zeros((n, n))
    reach[:3, :3] = 1
    reach[3:, 3:] = 1
    g = gm.form_groups(jnp.ones(n), jnp.asarray(reach))
    prio = gm.node_priority(n)
    assert int(g.n_groups) == 2
    c = np.asarray(g.coordinator)
    assert len(set(c[:3])) == 1 and len(set(c[3:])) == 1
    assert c[0] == np.argmax(prio[:3])
    assert c[3] == 3 + np.argmax(prio[3:])
    # No group spans the partition.
    assert np.asarray(g.group_mask)[:3, 3:].sum() == 0


def test_chain_diameter_converges():
    # A 16-node chain (diameter 15) must still form ONE group — the
    # adjacency-squaring propagation covers any diameter in O(log N).
    n = 16
    reach = np.zeros((n, n))
    for i in range(n - 1):
        reach[i, i + 1] = reach[i + 1, i] = 1
    g = gm.form_groups(jnp.ones(n), jnp.asarray(reach))
    assert int(g.n_groups) == 1
    assert len(set(np.asarray(g.coordinator))) == 1


def test_dead_node_excluded_and_counters():
    # Killing the leader forces an election (Recovery/Timeout path,
    # GroupManagement.cpp:437-465,851-893); counters reflect the change.
    n = 5
    g0 = gm.form_groups(jnp.ones(n), full_mesh(n))
    leader = int(g0.coordinator[0])
    alive = jnp.ones(n).at[leader].set(0.0)
    g1 = gm.form_groups(alive, full_mesh(n))
    assert int(g1.coordinator[leader]) == -1
    c = np.asarray(g1.coordinator)
    live = [i for i in range(n) if i != leader]
    assert len(set(c[live])) == 1 and c[live[0]] != leader
    counters = gm.diff_counters(g0, g1)
    assert int(counters.elections) == 1
    assert int(counters.groups_broken) > 0


def test_election_is_jittable_and_batchable():
    n = 6
    batch_alive = jnp.stack([jnp.ones(n), jnp.ones(n).at[0].set(0.0)])
    out = jax.vmap(lambda a: gm.form_groups(a, full_mesh(n)))(batch_alive)
    assert out.coordinator.shape == (2, n)


# ---------------------------------------------------------------------------
# topology: FID-gated reachability
# ---------------------------------------------------------------------------


def test_topology_parse_and_fid_gating():
    topo = topo_mod.parse_topology(TOPOLOGY_CFG)
    assert topo.n_vertices == 4
    assert topo.n_fids == 2
    assert topo.fid_names == ("FID_DA", "FID_BD")
    reach = topo_mod.make_reachability(topo)

    # Both FIDs closed: ring + chord, fully connected.
    r = reach(jnp.ones(2))
    assert float(jnp.min(r)) == 1.0
    # FID_DA open: chain a-b-c-d (still connected via b-d? FID_BD closed).
    r = reach(jnp.asarray([0.0, 1.0]))
    assert float(r[0, 3]) == 1.0
    # Both FIDs open: d only reaches via c.
    r = reach(jnp.zeros(2))
    assert float(r[0, 3]) == 1.0  # a-b-c-d chain intact
    # Cut the c-d edge instead: not FID controlled, so always present.

    # Node-level reachability follows uuid order; unknown FID state (0)
    # breaks the edge (ReachablePeers drops non-closed FID edges,
    # CPhysicalTopology.cpp:92-169).
    node_reach = topo_mod.node_reachability(
        topo, ("host4:50000", "host1:50000", "host2:50000", "host3:50000")
    )
    nr = node_reach(jnp.zeros(2))
    assert nr.shape == (4, 4)
    assert float(nr[0, 1]) == 1.0  # d..a via chain


def test_groups_never_span_open_fid():
    # The gm/topology integration the reference gets from BFS filtering
    # (GroupManagement.cpp:587-640): break the only link, groups split.
    cfg = """
edge a b
fid b c FID1
sst a h1:1
sst b h2:1
sst c h3:1
"""
    topo = topo_mod.parse_topology(cfg)
    node_reach = topo_mod.node_reachability(topo, ("h1:1", "h2:1", "h3:1"))
    g_closed = gm.form_groups(jnp.ones(3), node_reach(jnp.ones(1)))
    g_open = gm.form_groups(jnp.ones(3), node_reach(jnp.zeros(1)))
    assert int(g_closed.n_groups) == 1
    assert int(g_open.n_groups) == 2
    assert np.asarray(g_open.group_mask)[0, 2] == 0


# ---------------------------------------------------------------------------
# lb: vectorized draft auction
# ---------------------------------------------------------------------------


def test_three_node_convergence():
    # BASELINE.md config #1 class: 3 nodes, one supply, one demand;
    # one migration quantum per round until balanced — the trajectory of
    # the reference's 3000 ms LoadManage rounds.
    netgen = jnp.asarray([10.0, -10.0, 0.0])
    gw0 = jnp.zeros(3)
    gw, migs, states = lb.run_rounds(netgen, gw0, full_mesh(3), 1.0, 15)
    migs = np.asarray(migs)
    assert migs[:10].min() >= 1  # keeps migrating while imbalanced
    assert migs[-1] == 0  # converged: no migrations
    np.testing.assert_allclose(np.asarray(gw), [10.0, -10.0, 0.0], atol=1e-6)
    # Final states all NORMAL (inside the ±step band, LoadBalance.cpp:412-453).
    assert np.all(np.asarray(states[-1]) == lb.NORMAL)


def test_total_gateway_conserved_honest():
    # Honest migrations move power, never create it: Σ gateway constant.
    rng = np.random.default_rng(0)
    netgen = jnp.asarray(rng.normal(0, 5, 8))
    gw, _, _ = lb.run_rounds(netgen, jnp.zeros(8), full_mesh(8), 0.5, 30)
    assert float(jnp.sum(gw)) == pytest.approx(0.0, abs=1e-5)


def test_matching_respects_groups():
    # Supply in group A must not serve demand in group B (the auction
    # only runs over the coordinator's peer list).
    netgen = jnp.asarray([5.0, 0.0, -5.0, 0.0])
    group = np.zeros((4, 4))
    group[:2, :2] = 1  # {supply, normal}
    group[2:, 2:] = 1  # {demand, normal}
    out = lb.lb_round(netgen, jnp.zeros(4), jnp.asarray(group), 1.0)
    assert int(out.n_migrations) == 0
    np.testing.assert_allclose(np.asarray(out.gateway), np.zeros(4), atol=1e-7)


def test_rank_matching_pairs_distinct_partners():
    # Two supplies, two demands: both migrate in the same round to
    # *different* partners (the sequential reference needs two rounds;
    # outcome after its rounds is identical).
    netgen = jnp.asarray([4.0, 3.0, -5.0, -2.0])
    out = lb.lb_round(netgen, jnp.zeros(4), full_mesh(4), 1.0)
    m = np.asarray(out.matched)
    assert int(out.n_migrations) == 2
    assert m[:, 2].sum() == 1 and m[:, 3].sum() == 1  # each demand served once
    # Biggest supply paired with biggest deficit (DraftStandard max age).
    assert m[0, 2] == 1 and m[1, 3] == 1


def test_malicious_node_breaks_conservation_but_ledger_accounts():
    # --malicious-behavior: demand accepts but drops actuation
    # (LoadBalance.cpp:862-865). Raw Σ gateway drifts; the snapshot
    # invariant Σ gateway + Σ intransit stays conserved — exactly what
    # SC's in-transit accounting exists to catch.
    netgen = jnp.asarray([5.0, -5.0, 0.0])
    malicious = jnp.asarray([0.0, 1.0, 0.0])
    out = lb.lb_round(netgen, jnp.zeros(3), full_mesh(3), 1.0, malicious=malicious)
    assert float(jnp.sum(out.gateway)) == pytest.approx(1.0)  # drift!
    assert float(jnp.sum(out.gateway) + jnp.sum(out.intransit)) == pytest.approx(0.0)


def test_invariant_gate_blocks_migrations():
    # InvariantCheck gating (LoadBalance.cpp:1237-1277): gate low =>
    # classification still runs, nothing actuates.
    netgen = jnp.asarray([5.0, -5.0])
    out = lb.lb_round(netgen, jnp.zeros(2), full_mesh(2), 1.0, invariant_ok=jnp.zeros(()))
    assert int(out.n_migrations) == 0
    assert int(out.state[0]) == lb.SUPPLY  # still classified


# ---------------------------------------------------------------------------
# sc: consistent collection + conservation
# ---------------------------------------------------------------------------


def test_collect_sums_within_group_only():
    group = np.zeros((4, 4))
    group[:2, :2] = 1
    group[2:, 2:] = 1
    gw = jnp.asarray([1.0, 2.0, 4.0, 8.0])
    z = jnp.zeros(4)
    cs = sc.collect(jnp.asarray(group), gw, z, z, z, z, z)
    np.testing.assert_allclose(np.asarray(cs.gateway), [3.0, 3.0, 12.0, 12.0])
    assert np.asarray(cs.members).tolist() == [2, 2, 2, 2]


def test_snapshot_invariant_under_migrations():
    # Property: a cut taken at any round boundary sees
    # Σ gateway + Σ in-transit equal to the pre-round Σ gateway, for any
    # malicious mix — the migration quanta crossing the cut are exactly
    # the ledger. This is the Chandy-Lamport channel-state equivalence
    # (StateCollection.cpp:539-558) that lets LB Synchronize correctly
    # (LoadBalance.cpp:1160-1236).
    rng = np.random.default_rng(1)
    n = 6
    netgen = jnp.asarray(rng.normal(0, 4, n))
    malicious = jnp.asarray((rng.uniform(size=n) < 0.3).astype(np.float64))
    group = full_mesh(n)
    gw = jnp.zeros(n)
    for _ in range(10):
        before = float(jnp.sum(gw))
        out = lb.lb_round(netgen, gw, group, 0.5, malicious=malicious)
        cs = sc.collect(group, out.gateway, *(jnp.zeros(n),) * 4, out.intransit)
        np.testing.assert_allclose(
            np.asarray(sc.invariant_total(cs)), np.full(n, before), atol=1e-5
        )
        gw = out.gateway


def test_duplicate_fid_declaration_rejected():
    with pytest.raises(ValueError, match="duplicate fid"):
        topo_mod.parse_topology("edge a b\nfid a b F1\nfid b a F2\n")


def test_single_line_raw_topology_parses():
    # A marker-free one-liner is raw text, not a path (textio fix).
    topo = topo_mod.parse_topology("edge a b")
    assert topo.vertices == ("a", "b")


def test_form_groups_with_raw_hash_priorities():
    # Raw 32-bit UUID-hash magnitudes must not collide in float32: the
    # kernel rank-compresses internally.
    n = 6
    base = np.uint64(2**31)
    prio = jnp.asarray((base + np.arange(n, dtype=np.uint64) * 3).astype(np.float64))
    g = gm.form_groups(jnp.ones(n), jnp.ones((n, n)), prio)
    # Highest raw priority (last index) coordinates the single group.
    assert int(g.n_groups) == 1
    assert np.asarray(g.coordinator).tolist() == [n - 1] * n


# ---------------------------------------------------------------------------
# lb: the sorted-matching round vs the O(N^2) pairwise reference
# ---------------------------------------------------------------------------


def _pairwise_lb_round(net_generation, gateway, group_mask, step,
                       malicious=None, invariant_ok=None):
    """The pre-optimization O(N^2) round (pairwise comparison matrices),
    kept verbatim as the oracle the sort-based `lb.lb_round` must match
    outcome-for-outcome (BENCH `lb_256node_rounds_per_sec` hot path)."""
    n = gateway.shape[0]
    state = lb.classify(net_generation, gateway, step)
    is_supply = (state == lb.SUPPLY).astype(jnp.float32)
    is_demand = (state == lb.DEMAND).astype(jnp.float32)
    malicious = (
        jnp.zeros(n) if malicious is None else malicious.astype(jnp.float32)
    )
    gate = jnp.ones(()) if invariant_ok is None else jnp.asarray(invariant_ok)
    gate = jnp.broadcast_to(gate, (n,)).astype(jnp.float32)
    age = jnp.maximum(gateway - net_generation, 0.0) * is_demand
    surplus = jnp.maximum(net_generation - gateway, 0.0) * is_supply
    s_rank = lb._group_rank(surplus, is_supply * gate, group_mask)
    d_rank = lb._group_rank(age, is_demand * gate, group_mask)
    eligible = (age >= step).astype(jnp.float32)
    pair = (
        (s_rank[:, None] == d_rank[None, :]).astype(jnp.float32)
        * (s_rank[:, None] < n).astype(jnp.float32)
        * group_mask
        * is_supply[:, None]
        * (is_demand * eligible)[None, :]
    )
    supply_delta = jnp.sum(pair, axis=1) * step
    demand_applied = jnp.sum(pair, axis=0) * step * (1.0 - malicious)
    demand_accepted = jnp.sum(pair, axis=0) * step
    return lb.LBRound(
        state=state,
        gateway=gateway + supply_delta - demand_applied,
        matched=pair,
        supply_step=supply_delta,
        demand_step=-demand_applied,
        intransit=demand_applied - demand_accepted,
        n_migrations=jnp.sum(pair).astype(jnp.int32),
    )


def _random_partition_mask(rng, n, n_groups):
    gid = rng.integers(0, n_groups, n)
    return jnp.asarray((gid[:, None] == gid[None, :]).astype(np.float32))


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_sorted_round_matches_pairwise_reference(seed):
    rng = np.random.default_rng(seed)
    n = 64
    mask = _random_partition_mask(rng, n, rng.integers(1, 9))
    netgen = jnp.asarray(rng.normal(0, 10, n).astype(np.float32))
    gw = jnp.asarray(rng.normal(0, 2, n).astype(np.float32))
    mal = jnp.asarray((rng.uniform(size=n) < 0.2).astype(np.float32))
    got = lb.lb_round(netgen, gw, mask, 1.0, malicious=mal)
    want = _pairwise_lb_round(netgen, gw, mask, 1.0, malicious=mal)
    np.testing.assert_array_equal(np.asarray(got.state), np.asarray(want.state))
    np.testing.assert_allclose(
        np.asarray(got.gateway), np.asarray(want.gateway), atol=1e-5
    )
    np.testing.assert_array_equal(
        np.asarray(got.matched), np.asarray(want.matched)
    )
    np.testing.assert_allclose(
        np.asarray(got.supply_step), np.asarray(want.supply_step), atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(got.demand_step), np.asarray(want.demand_step), atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(got.intransit), np.asarray(want.intransit), atol=1e-6
    )
    assert int(got.n_migrations) == int(want.n_migrations)


def test_sorted_round_trajectory_matches_pairwise_to_convergence():
    # The whole convergence trajectory (the bench workload), not just
    # one round: per-round migration counts and final gateways agree.
    rng = np.random.default_rng(3)
    n = 48
    mask = _random_partition_mask(rng, n, 4)
    netgen = jnp.asarray(rng.normal(0, 10, n).astype(np.float32))
    gw = jnp.zeros(n, jnp.float32)
    gw_ref = gw
    for _ in range(40):
        got = lb.lb_round(netgen, gw, mask, 1.0)
        want = _pairwise_lb_round(netgen, gw_ref, mask, 1.0)
        assert int(got.n_migrations) == int(want.n_migrations)
        np.testing.assert_allclose(
            np.asarray(got.gateway), np.asarray(want.gateway), atol=1e-4
        )
        gw, gw_ref = got.gateway, want.gateway
    assert int(got.n_migrations) == 0  # converged within the budget
