"""Telemetry tests (SURVEY §5: "JAX profiler + per-step telemetry
arrays" — the stated replacement for the reference's Trace-level
call-entry logging and offline log spreadsheets)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from freedm_tpu.runtime.telemetry import Telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_ring_records_and_wraps():
    t = Telemetry(capacity=4)
    for i in range(6):
        t.record(round=i, wall_s=0.01 * (i + 1), migrations=i)
    assert len(t) == 4
    d = t.asdict()
    # Chronological order, oldest first, wrapped past capacity.
    np.testing.assert_allclose(d["round"], [2, 3, 4, 5])
    np.testing.assert_allclose(d["migrations"], [2, 3, 4, 5])
    # Unset columns read NaN, not stale garbage.
    assert np.all(np.isnan(d["vvc_loss_kw"]))
    s = t.summary()
    assert s["rounds"] == 6
    assert s["round_ms_p50"] == pytest.approx(45.0)
    assert s["last_migrations"] == 5


def test_summary_empty():
    assert Telemetry().summary() == {"rounds": 0}


def test_cli_records_per_round_telemetry(tmp_path):
    """A config-driven run carries round-time percentiles in its
    summaries and fills the per-phase columns."""
    from test_checkpoint import write_rig

    cfg = write_rig(tmp_path)
    from freedm_tpu.cli import build_runtime

    rt = build_runtime(cfg).start()
    try:
        rt.broker.run(n_rounds=8)
        tel = rt.telemetry.telemetry
        assert len(tel) == 8
        d = tel.asdict()
        # Phase wall-times recorded from round 0; full-round wall from 1.
        assert np.all(np.isfinite(d["gm_ms"]))
        assert np.all(np.isfinite(d["lb_ms"]))
        assert np.sum(np.isfinite(d["wall_s"])) == 7
        assert np.all(d["n_groups"] == 1)
        s = tel.summary()
        assert "round_ms_p50" in s and s["round_ms_p50"] > 0
    finally:
        rt.stop()


def test_profile_trace_writes_a_trace(tmp_path):
    """--profile-dir captures a JAX profiler trace (subprocess: the
    profiler is process-global and must not leak into other tests)."""
    from test_checkpoint import write_rig

    cfg = write_rig(tmp_path)
    cfg_file = tmp_path / "freedm.cfg"
    cfg_file.write_text(
        "add-host = nodeB:50811\n"
        f"device-config = {cfg.device_config}\n"
        f"adapter-config = {cfg.adapter_config}\n"
        "migration-step = 1\n"
    )
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-m", "freedm_tpu", "-c", str(cfg_file),
         "--rounds", "3", "--summary-every", "1",
         "--profile-dir", str(tmp_path / "trace")],
        capture_output=True, env=env, text=True, timeout=300,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [json.loads(l) for l in out.stdout.splitlines() if l.startswith("{")]
    assert len(lines) == 3
    assert "round_ms_p50" in lines[-1]
    # The profiler wrote a trace artifact.
    found = []
    for root, _dirs, files in os.walk(tmp_path / "trace"):
        found += files
    assert found, "no profiler trace written"
