"""gridlint tests: one violating + one clean fixture per rule
(GL001-GL006), suppression-comment semantics, the JSON output schema,
and the repo-wide self-lint contract (the shipped tree lints clean,
the GL006 lock graph covers every lock-holding module, zero cycles).

Fixtures are small synthetic projects written into ``tmp_path``; the
cross-file rules (GL004/GL005) get miniature ``core/config.py`` /
``cli.py`` / ``docs/*.md`` layouts, and GL002 fixtures reuse the real
hot-path registry's module/qualname coordinates.
"""

import json
import pathlib
import textwrap

from freedm_tpu.tools.gridlint import main, run_lint

REPO = pathlib.Path(__file__).resolve().parent.parent


def _write(root: pathlib.Path, rel: str, src: str) -> None:
    p = root / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(src))


def _lint(root: pathlib.Path, *paths, rules=None):
    targets = [str(root / p) for p in paths] if paths else [str(root)]
    return run_lint(targets, root=str(root), rules=rules)


def _rules_of(result):
    return sorted({f.rule for f in result.findings})


# ---------------------------------------------------------------------------
# GL001 jit purity
# ---------------------------------------------------------------------------

GL001_BAD = """
    import time
    import numpy as np
    import jax
    from jax import lax

    def sweep(xs):
        def step(carry, x):
            t = time.time()
            return carry + x + np.asarray(t), x
        return lax.scan(step, 0.0, xs)

    @jax.jit
    def solve(x):
        print("tracing", x)
        return x * np.random.normal()
"""

GL001_CLEAN = """
    import time
    import jax
    import jax.numpy as jnp
    from jax import lax

    def sweep(xs):
        t0 = time.time()  # host side: before the traced region
        def step(carry, x):
            return carry + jnp.sin(x), x
        return lax.scan(step, 0.0, xs), time.time() - t0

    def helper(x):
        print(x)  # not traced: plain host helper
        return x
"""


def test_gl001_flags_impure_calls_in_traced_bodies(tmp_path):
    _write(tmp_path, "mod.py", GL001_BAD)
    res = _lint(tmp_path, "mod.py")
    assert _rules_of(res) == ["GL001"]
    msgs = " ".join(f.message for f in res.findings)
    assert "time.time" in msgs and "numpy.asarray" in msgs
    assert "print" in msgs and "numpy.random.normal" in msgs
    assert main([str(tmp_path / "mod.py"), "--root", str(tmp_path)]) == 1


def test_gl001_clean_fixture_passes(tmp_path):
    _write(tmp_path, "mod.py", GL001_CLEAN)
    res = _lint(tmp_path, "mod.py")
    assert res.findings == []
    assert main([str(tmp_path / "mod.py"), "--root", str(tmp_path)]) == 0


# ---------------------------------------------------------------------------
# GL002 hot-path syncs (coordinates match the real registry entries)
# ---------------------------------------------------------------------------

GL002_BAD = """
    class ExecutorLane:
        def _run(self):
            pass

    class MicroBatcher:
        def _run(self):
            pass

        def _run_serial(self):
            pass

        def _run_pipelined(self):
            pass

        def _dispatch(self, group, lanes):
            pass

        def _assemble(self, group, lanes):
            batch.block_until_ready()       # assembly lane must not sync

        def _execute(self, work):
            results = engine.solve(batch)
            worst = float(results[0])       # device sync before the boundary
            x = results.item()              # device sync
            return worst
"""

GL002_CLEAN = """
    import jax


    class ExecutorLane:
        def _run(self):
            pass

    class MicroBatcher:
        def _run(self):
            pass

        def _run_serial(self):
            pass

        def _run_pipelined(self):
            pass

        def _dispatch(self, group, lanes):
            pass

        def _assemble(self, group, lanes):
            batch = engine.assemble(group, bucket)  # host numpy only
            return batch

        def _execute(self, work):
            results = engine.solve(batch)
            jax.block_until_ready(results)  # THE designed deferred sync
            engine.scatter(group, results, info)  # results stay on device
            queue_ms = float(123)  # host arithmetic is fine
"""


def test_gl002_flags_syncs_in_declared_hot_path(tmp_path):
    _write(tmp_path, "freedm_tpu/serve/batcher.py", GL002_BAD)
    res = _lint(tmp_path, rules=["GL002"])
    assert _rules_of(res) == ["GL002"]
    msgs = " ".join(f.message for f in res.findings)
    assert "float()" in msgs and ".item()" in msgs
    assert "block_until_ready" in msgs
    assert main([str(tmp_path), "--root", str(tmp_path),
                 "--rules", "GL002"]) == 1


def test_gl002_clean_fixture_passes(tmp_path):
    _write(tmp_path, "freedm_tpu/serve/batcher.py", GL002_CLEAN)
    res = _lint(tmp_path, rules=["GL002"])
    assert res.findings == []


# ---------------------------------------------------------------------------
# GL003 chunk purity
# ---------------------------------------------------------------------------

GL003_BAD = """
    import time
    import numpy as np

    class ProfileSet:
        def __init__(self, spec):
            self.rng = np.random.default_rng(spec)
            self.scale = self.rng.lognormal(0.0, 1.0)

        def load_chunk(self, t0, t1):
            return self.rng.normal(size=t1 - t0)  # draw outside __init__

    def checkpoint_key(spec):
        return _stamp(spec)

    def _stamp(spec):
        return f"{spec}-{time.time()}"  # clock feeds checkpoint identity
"""

GL003_CLEAN = """
    import numpy as np

    class ProfileSet:
        def __init__(self, spec):
            rng = np.random.default_rng(spec)
            self.scale = rng.lognormal(0.0, 1.0)
            self.phase = rng.uniform(0.0, 1.0, 8)

        def load_chunk(self, t0, t1):
            t = np.arange(t0, t1)
            return self.scale * np.sin(t + self.phase[0])

    def checkpoint_key(spec):
        return f"study-{spec}"
"""


def test_gl003_flags_rng_and_clock_leaks(tmp_path):
    _write(tmp_path, "scenarios/profiles.py", GL003_BAD)
    res = _lint(tmp_path, rules=["GL003"])
    assert _rules_of(res) == ["GL003"]
    msgs = " ".join(f.message for f in res.findings)
    assert "outside __init__" in msgs
    assert "time.time" in msgs and "checkpoint identity" in msgs
    assert main([str(tmp_path), "--root", str(tmp_path),
                 "--rules", "GL003"]) == 1


def test_gl003_clean_fixture_passes(tmp_path):
    _write(tmp_path, "scenarios/profiles.py", GL003_CLEAN)
    res = _lint(tmp_path, rules=["GL003"])
    assert res.findings == []


# agents.py is policed by the same construction-only contract: draws
# are allowed only in the declared seams (__init__, population_rng,
# build_population); a draw in an agent step breaks chunk-invariant
# resume exactly like one in a profile chunk.
GL003_AGENTS_BAD = """
    import numpy as np

    def population_rng(seed, stream):
        return np.random.default_rng(seed)

    def build_population(spec):
        rng = population_rng(spec, "agents")
        return rng.uniform(0.0, 1.0, 8)

    def ev_step(soc, obs_v, h):
        rng = np.random.default_rng(0)
        return soc + rng.normal()  # draw inside a step function
"""

GL003_AGENTS_CLEAN = """
    import numpy as np

    def population_rng(seed, stream):
        return np.random.default_rng(seed)

    def build_population(spec):
        rng = population_rng(spec, "agents")
        return rng.uniform(0.0, 1.0, 8)

    def ev_step(soc, obs_v, h, prm):
        return min(soc + prm * obs_v * h, 1.0)
"""


def test_gl003_flags_agent_step_draws(tmp_path):
    _write(tmp_path, "scenarios/agents.py", GL003_AGENTS_BAD)
    res = _lint(tmp_path, rules=["GL003"])
    assert _rules_of(res) == ["GL003"]
    msgs = " ".join(f.message for f in res.findings)
    assert "ev_step" in msgs and "outside __init__" in msgs


def test_gl003_agents_construction_seams_pass(tmp_path):
    _write(tmp_path, "scenarios/agents.py", GL003_AGENTS_CLEAN)
    res = _lint(tmp_path, rules=["GL003"])
    assert res.findings == []


# ---------------------------------------------------------------------------
# GL004 config threading
# ---------------------------------------------------------------------------

GL004_CONFIG = """
    from dataclasses import dataclass

    @dataclass(frozen=True)
    class GlobalConfig:
        port: int = 1
        ghost_key: str = "x"
"""

GL004_CLI_BAD = """
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int)
    ap.add_argument("--stray-flag")
"""

GL004_DOCS_BAD = """
    ## freedm.cfg
    ```ini
    port = 1
    removed-key = 2
    ```
"""


def test_gl004_flags_unthreaded_keys_both_directions(tmp_path):
    _write(tmp_path, "core/config.py", GL004_CONFIG)
    _write(tmp_path, "cli.py", GL004_CLI_BAD)
    _write(tmp_path, "docs/configuration.md", GL004_DOCS_BAD)
    res = _lint(tmp_path, rules=["GL004"])
    msgs = [f.message for f in res.findings]
    assert any("`ghost_key` has no `--ghost-key`" in m for m in msgs)
    assert any("`ghost_key` is not documented" in m for m in msgs)
    assert any("--stray-flag" in m for m in msgs)
    assert any("removed-key" in m for m in msgs)
    assert main([str(tmp_path), "--root", str(tmp_path),
                 "--rules", "GL004"]) == 1


def test_gl004_clean_fixture_passes(tmp_path):
    _write(tmp_path, "core/config.py", """
        from dataclasses import dataclass

        @dataclass(frozen=True)
        class GlobalConfig:
            port: int = 1
    """)
    _write(tmp_path, "cli.py", """
        import argparse
        ap = argparse.ArgumentParser()
        ap.add_argument("--port", type=int)
        ap.add_argument("--rounds", type=int)  # declared runtime-only
    """)
    _write(tmp_path, "docs/configuration.md", """
        ## freedm.cfg
        ```ini
        port = 1
        ```
    """)
    res = _lint(tmp_path, rules=["GL004"])
    assert res.findings == []


# ---------------------------------------------------------------------------
# GL005 metric/event/span drift
# ---------------------------------------------------------------------------

GL005_METRICS = """
    import threading

    class MetricsRegistry:
        def counter(self, name, help=""):
            return self

    REGISTRY = MetricsRegistry()
    GHOST = REGISTRY.counter("ghost_metric_total", "undocumented")
    OK = REGISTRY.counter("ok_metric_total", "documented")

    class Journal:
        def emit(self, event, **kw):
            pass

    EVENTS = Journal()

    def fire():
        EVENTS.emit("ghost.event", x=1)
        EVENTS.emit("ok.event", x=1)
"""

GL005_DOCS = """
    | Metric | Type | Meaning |
    |---|---|---|
    | `ok_metric_total` | counter | fine |
    | `orphan_metric_total` | counter | registered nowhere |

    | Event | Emitted when | Extra fields |
    |---|---|---|
    | `ok.event` | fine | |
    | `orphan.event` | emitted nowhere | |
"""


def test_gl005_flags_drift_both_directions(tmp_path):
    _write(tmp_path, "core/metrics.py", GL005_METRICS)
    _write(tmp_path, "docs/observability.md", GL005_DOCS)
    res = _lint(tmp_path, rules=["GL005"])
    msgs = [f.message for f in res.findings]
    assert any("`ghost_metric_total` is registered" in m for m in msgs)
    assert any("`ghost.event` is emitted" in m for m in msgs)
    assert any("orphan doc row: metric `orphan_metric_total`" in m
               for m in msgs)
    assert any("orphan doc row: event `orphan.event`" in m for m in msgs)
    assert main([str(tmp_path), "--root", str(tmp_path),
                 "--rules", "GL005"]) == 1


def test_gl005_clean_fixture_passes(tmp_path):
    _write(tmp_path, "core/metrics.py", """
        class MetricsRegistry:
            def counter(self, name, help=""):
                return self

        REGISTRY = MetricsRegistry()
        OK = REGISTRY.counter("ok_metric_total", "documented")
    """)
    _write(tmp_path, "docs/observability.md", """
        | Metric | Type | Meaning |
        |---|---|---|
        | `ok_metric_total` | counter | fine |
    """)
    res = _lint(tmp_path, rules=["GL005"])
    assert res.findings == []


# ---------------------------------------------------------------------------
# GL006 lock order
# ---------------------------------------------------------------------------

GL006_BAD = """
    import threading

    class A:
        def __init__(self):
            self._lock = threading.Lock()

        def m(self):
            with self._lock:
                B_SINGLETON.f()

        def g(self):
            with self._lock:
                pass

    class B:
        def __init__(self):
            self._lock = threading.Lock()

        def f(self):
            with self._lock:
                A_SINGLETON.g()

        def run(self, on_done):
            with self._lock:
                on_done()  # callback invoked under the lock

    A_SINGLETON = A()
    B_SINGLETON = B()
"""

GL006_CLEAN = """
    import threading

    class A:
        def __init__(self):
            self._lock = threading.Lock()

        def m(self):
            with self._lock:
                B_SINGLETON.f()  # one direction only: A -> B

        def run(self, on_done):
            with self._lock:
                snapshot = 1
            on_done(snapshot)  # callback after release

    class B:
        def __init__(self):
            self._lock = threading.Lock()

        def f(self):
            with self._lock:
                pass

    A_SINGLETON = A()
    B_SINGLETON = B()
"""


def test_gl006_flags_cycles_and_callbacks_under_lock(tmp_path):
    _write(tmp_path, "mod.py", GL006_BAD)
    res = _lint(tmp_path, rules=["GL006"])
    msgs = [f.message for f in res.findings]
    assert any("lock-order cycle" in m for m in msgs)
    assert any("callback-shaped call `on_done`" in m for m in msgs)
    graph = res.artifacts["lock_graph"]
    assert ["mod.py:A._lock", "mod.py:B._lock"] in graph["edges"]
    assert ["mod.py:B._lock", "mod.py:A._lock"] in graph["edges"]
    assert graph["cycles"]
    assert main([str(tmp_path), "--root", str(tmp_path),
                 "--rules", "GL006"]) == 1


def test_gl006_clean_fixture_passes_and_exports_graph(tmp_path):
    _write(tmp_path, "mod.py", GL006_CLEAN)
    res = _lint(tmp_path, rules=["GL006"])
    assert res.findings == []
    graph = res.artifacts["lock_graph"]
    assert graph["edges"] == [["mod.py:A._lock", "mod.py:B._lock"]]
    assert graph["cycles"] == []
    assert graph["modules"] == ["mod.py"]


# ---------------------------------------------------------------------------
# suppression semantics
# ---------------------------------------------------------------------------


def test_suppression_comment_semantics(tmp_path):
    _write(tmp_path, "mod.py", """
        import time
        import jax

        @jax.jit
        def solve(x):
            a = time.time()  # gridlint: disable=GL001
            # gridlint: disable
            b = time.time()
            c = time.time()  # gridlint: disable=GL002
            return x
    """)
    res = _lint(tmp_path, "mod.py")
    # Inline id-match and standalone-above suppress; a mismatched rule
    # id does not.
    assert len(res.findings) == 1
    assert res.findings[0].rule == "GL001"
    assert res.findings[0].line == 10


# ---------------------------------------------------------------------------
# JSON schema + CLI behavior
# ---------------------------------------------------------------------------


def test_json_output_schema(tmp_path, capsys):
    _write(tmp_path, "mod.py", GL001_BAD)
    rc = main([str(tmp_path / "mod.py"), "--root", str(tmp_path),
               "--format", "json"])
    assert rc == 1
    out = json.loads(capsys.readouterr().out)
    assert out["version"] == 1
    assert isinstance(out["findings"], list) and out["findings"]
    f = out["findings"][0]
    assert set(f) == {"rule", "path", "line", "col", "message", "hint"}
    stats = out["stats"]
    assert stats["files"] == 1
    assert stats["findings_total"] == len(out["findings"])
    assert stats["findings_by_rule"].get("GL001") == len(out["findings"])
    assert "lock_graph" in stats  # GL006 artifact rides the stats block


def test_parse_error_is_a_finding_not_a_crash(tmp_path):
    _write(tmp_path, "broken.py", "def oops(:\n")
    res = _lint(tmp_path, "broken.py")
    assert [f.rule for f in res.findings] == ["GL000"]


def test_list_rules_and_unknown_path(capsys):
    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in ("GL001", "GL002", "GL003", "GL004", "GL005", "GL006"):
        assert rid in out


# ---------------------------------------------------------------------------
# the repo-wide contract
# ---------------------------------------------------------------------------


def test_self_lint_repo_is_clean_and_lock_graph_covers_modules():
    targets = [str(REPO / "freedm_tpu"), str(REPO / "tests"),
               str(REPO / "bench.py")]
    res = run_lint(targets, root=str(REPO))
    assert res.findings == [], "\n".join(
        f"{f.path}:{f.line}: {f.rule} {f.message}" for f in res.findings
    )
    graph = res.artifacts["lock_graph"]
    # The acceptance bar: every lock-holding module is in the graph
    # (14+ at the time this rule landed) and the order is acyclic.
    assert len(graph["modules"]) >= 14
    assert graph["cycles"] == []
    # The cross-checked serve/jobs -> metrics edges are derived.
    edges = {tuple(e) for e in graph["edges"]}
    assert ("freedm_tpu/scenarios/jobs.py:JobManager._cond",
            "freedm_tpu/core/metrics.py:_Metric._lock") in edges


def test_gridlint_findings_metric_records_in_process():
    from freedm_tpu.core import metrics as obs
    from freedm_tpu.tools.gridlint import record_metrics

    res = run_lint([str(REPO / "freedm_tpu" / "tools" / "gridlint.py")],
                   root=str(REPO))
    record_metrics(res)  # clean tree: counter exists, stays untouched
    m = obs.REGISTRY.get("gridlint_findings_total")
    assert m is not None and m.kind == "counter"


# ---------------------------------------------------------------------------
# review regressions: switch branch lists, inherited locks, loop taint
# ---------------------------------------------------------------------------


def test_gl001_switch_branch_list_and_cond_operands(tmp_path):
    _write(tmp_path, "mod.py", """
        import time
        import jax
        from jax import lax

        def branch_a(x):
            return x + time.time()  # impure switch branch

        def branch_b(x):
            return x * 2.0

        def helper(x):
            print(x)  # host helper used as an OPERAND, not a branch
            return x

        def dispatch(i, x, p):
            y = lax.switch(i, [branch_a, branch_b], x)
            return lax.cond(p, branch_b, branch_b, helper(x)) + y
    """)
    res = _lint(tmp_path, "mod.py", rules=["GL001"])
    msgs = [f.message for f in res.findings]
    # branch_a IS traced via the switch branch list...
    assert any("time.time" in m and "branch_a" in m for m in msgs)
    # ...but the cond operand expression must NOT drag helper in.
    assert not any("helper" in m for m in msgs)


def test_gl006_inherited_lock_resolves_to_declaring_class(tmp_path):
    _write(tmp_path, "mod.py", """
        import threading

        class Base:
            def __init__(self):
                self._lock = threading.Lock()

        class Child(Base):
            def meth(self):
                with self._lock:
                    self.on_done()

            def on_done(self):
                pass
    """)
    res = _lint(tmp_path, "mod.py", rules=["GL006"])
    # The inherited lock is attributed to Base (the declaring class),
    # so the callback-under-lock trap is visible from the subclass.
    assert any("callback-shaped call `on_done`" in f.message
               and "Base._lock" in f.message for f in res.findings)


def test_gl002_for_loop_over_device_result_taints_target(tmp_path):
    _write(tmp_path, "freedm_tpu/serve/batcher.py", """
        class ExecutorLane:
            def _run(self):
                pass

        class MicroBatcher:
            def _run(self):
                pass

            def _run_serial(self):
                pass

            def _run_pipelined(self):
                pass

            def _dispatch(self, group, lanes):
                pass

            def _assemble(self, group, lanes):
                pass

            def _execute(self, work):
                results = engine.solve(batch)
                out = []
                for row in results:
                    out.append(float(row))  # per-lane device sync
                return out
    """)
    res = _lint(tmp_path, rules=["GL002"])
    assert any("float()" in f.message for f in res.findings)
