"""Real-case validation against published IEEE solutions (VERDICT r4
missing item 3).

The reference's numeric credibility came from HIL regression artifacts
(``/root/reference/Broker/testing/results/``); the framework equivalent
is solving recognized public cases and pinning the answers to their
published values:

- **case14** — the bus matrix carries the published solved operating
  point (Vm/Va columns of the IEEE distribution), so the test is a
  value-level oracle: |V| to ~1e-3 (the published values are rounded to
  3 decimals) and angles to ~0.05 deg, plus the two classic aggregates
  (slack generation 232.4 MW, system losses 13.39 MW).
- **case_ieee30** — no offline copy of the published per-bus solution
  exists in this environment, so the anchors are the published
  aggregates (total load 283.4 MW, base-case losses 17.557 MW, slack
  generation ~260.95 MW) plus cross-solver agreement.

Cross-solver agreement (full Newton vs fast-decoupled, two different
iterations sharing only the Ybus) guards against a systematic error
that a single solver's convergence test would miss.
"""

import numpy as np

from freedm_tpu.grid.matpower import (
    builtin_case_names,
    builtin_solved_state,
    load_builtin,
)
from freedm_tpu.pf.fdlf import make_fdlf_solver
from freedm_tpu.pf.newton import make_newton_solver

F64 = np.float64


def test_builtin_cases_present():
    names = builtin_case_names()
    assert "case14" in names and "case_ieee30" in names


def test_case14_matches_published_solution():
    sys14 = load_builtin("case14")
    assert sys14.n_bus == 14 and sys14.n_branch == 20
    solve, _ = make_newton_solver(sys14, dtype=F64, max_iter=15)
    r = solve()
    assert bool(r.converged)
    vm_pub, va_pub = builtin_solved_state("case14")
    vm = np.asarray(r.v)
    va = np.degrees(np.asarray(r.theta))
    # Published values are rounded to 3 decimals (1e-3 / 1e-2 deg).
    np.testing.assert_allclose(vm, vm_pub, atol=2e-3)
    np.testing.assert_allclose(va, va_pub, atol=5e-2)
    # The two classic aggregates of the case14 base case.
    assert abs(float(r.p[0]) * 100.0 - 232.4) < 0.2  # slack generation, MW
    assert abs(float(np.sum(r.p)) * 100.0 - 13.39) < 0.1  # losses, MW


def test_case14_fdlf_agrees_with_newton():
    sys14 = load_builtin("case14")
    nr, _ = make_newton_solver(sys14, dtype=F64, max_iter=15)
    fd, _ = make_fdlf_solver(sys14, dtype=F64, max_iter=60)
    a, b = nr(), fd()
    assert bool(a.converged) and bool(b.converged)
    np.testing.assert_allclose(np.asarray(a.v), np.asarray(b.v), atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(a.theta), np.asarray(b.theta), atol=1e-6
    )


def test_case30_published_aggregates_and_cross_solver():
    sys30 = load_builtin("case_ieee30")
    assert sys30.n_bus == 30 and sys30.n_branch == 41
    # Data-level anchor: the IEEE 30-bus total load is exactly 283.4 MW
    # (p_inj = gen - load; PQ buses carry pure load here, and the only
    # demand at a generator bus is netted against its dispatch).
    mpc_load = 21.7 + 2.4 + 7.6 + 94.2 + 22.8 + 30 + 5.8 + 11.2 + 6.2
    mpc_load += 8.2 + 3.5 + 9 + 3.2 + 9.5 + 2.2 + 17.5 + 3.2 + 8.7
    mpc_load += 3.5 + 2.4 + 10.6
    assert abs(mpc_load - 283.4) < 1e-9
    solve, _ = make_newton_solver(sys30, dtype=F64, max_iter=15)
    r = solve()
    assert bool(r.converged)
    losses_mw = float(np.sum(r.p)) * sys30.base_mva
    slack_mw = float(r.p[0]) * sys30.base_mva
    # Published base-case losses ~17.557 MW; slack picks up load - 40 +
    # losses = 260.96 MW.
    assert abs(losses_mw - 17.557) < 0.15
    assert abs(slack_mw - 260.96) < 0.3
    assert 0.99 < float(np.min(r.v)) and float(np.max(r.v)) <= 1.083

    fd, _ = make_fdlf_solver(sys30, dtype=F64, max_iter=80)
    b = fd()
    assert bool(b.converged)
    np.testing.assert_allclose(np.asarray(r.v), np.asarray(b.v), atol=1e-6)


def test_case30_n1_screen_converges_on_secure_outages():
    """A real-case N-1 screen: every non-islanding single-branch outage
    of the IEEE 30-bus system solves (vmap over status lanes)."""
    import jax
    import jax.numpy as jnp

    from freedm_tpu.pf.n1 import secure_outages

    sys30 = load_builtin("case_ieee30")
    secure = secure_outages(sys30)
    assert len(secure) >= 30  # the screen is not vacuous
    _, solve_fixed = make_newton_solver(sys30, dtype=F64, max_iter=8)
    status = np.ones((len(secure), sys30.n_branch), F64)
    status[np.arange(len(secure)), secure] = 0.0
    batched = jax.jit(jax.vmap(lambda s: solve_fixed(status=s)))
    r = batched(jnp.asarray(status))
    assert bool(np.all(np.asarray(r.converged)))
    # Outages only redistribute flow: voltages stay physical (the worst
    # secure case30 outage sags to ~0.86 pu — stressed, not collapsed).
    assert float(np.min(np.asarray(r.v))) > 0.8
