"""Unified observability subsystem tests (``freedm_tpu.core.metrics``):
registry counter/gauge/histogram semantics, SrChannel transport counters
under a lossy frame sequence, journal append/rotation, and a live
``--metrics-port`` scrape returning parseable Prometheus text.
"""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from freedm_tpu.core import metrics as M


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------


def test_counter_semantics():
    reg = M.MetricsRegistry()
    c = reg.counter("jobs_total", "help text")
    c.inc()
    c.inc(2.5)
    assert c.value == pytest.approx(3.5)
    with pytest.raises(ValueError):
        c.inc(-1)  # counters only go up
    # Idempotent re-registration returns the SAME metric...
    assert reg.counter("jobs_total", "help text") is c
    # ...but a kind or label clash is a hard error.
    with pytest.raises(ValueError):
        reg.gauge("jobs_total")
    with pytest.raises(ValueError):
        reg.counter("jobs_total", labels=("peer",))


def test_gauge_semantics():
    reg = M.MetricsRegistry()
    g = reg.gauge("depth", "")
    g.set(5)
    g.inc(2)
    g.dec(3)
    assert g.value == pytest.approx(4.0)
    g.set(-1.5)  # gauges may go negative
    assert g.value == pytest.approx(-1.5)


def test_labeled_children_are_independent():
    reg = M.MetricsRegistry()
    c = reg.counter("sent_total", "", labels=("peer",))
    c.labels("a").inc()
    c.labels("a").inc()
    c.labels("b").inc()
    assert c.labels("a").value == 2
    assert c.labels("b").value == 1
    with pytest.raises(ValueError):
        c.labels()  # wrong label arity
    text = reg.render_prometheus()
    assert 'sent_total{peer="a"} 2' in text
    assert 'sent_total{peer="b"} 1' in text


def test_histogram_buckets_and_render():
    reg = M.MetricsRegistry()
    h = reg.histogram("lat_seconds", "", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    h.observe(np.asarray([0.01, 0.02]))  # array observation, one call
    assert h.count == 5
    assert h.sum == pytest.approx(5.58)
    text = reg.render_prometheus()
    assert 'lat_seconds_bucket{le="0.1"} 3' in text
    assert 'lat_seconds_bucket{le="1"} 4' in text
    assert 'lat_seconds_bucket{le="+Inf"} 5' in text
    assert "lat_seconds_count 5" in text
    # A value exactly ON a bound lands in that bound's bucket (le is <=).
    h2 = reg.histogram("edge_seconds", "", buckets=(1.0,))
    h2.observe(1.0)
    assert 'edge_seconds_bucket{le="1"} 1' in reg.render_prometheus()


def test_snapshot_is_json_serializable():
    reg = M.MetricsRegistry()
    reg.counter("a_total").inc(3)
    reg.gauge("b", labels=("k",)).labels("v").set(7)
    reg.histogram("c_seconds", buckets=(1.0,)).observe(0.5)
    snap = json.loads(json.dumps(reg.snapshot()))
    assert snap["a_total"]["values"][""] == 3
    assert snap["b"]["values"]["v"] == 7
    assert snap["c_seconds"]["values"][""]["count"] == 1


def test_estimate_quantiles_from_fixed_buckets():
    # Buckets (1, 2, 4) + overflow; one observation per finite bucket.
    qs = M.estimate_quantiles((1.0, 2.0, 4.0), (1, 1, 1, 0), (0.5, 1.0))
    # target 1.5 of 3 selects the (1, 2] bucket's ONLY sample, whose
    # rank-anchored position is the bucket bound itself (not 1.5, the
    # midpoint the pre-fix interpolation reported).
    assert qs[0] == pytest.approx(2.0)
    assert qs[1] == pytest.approx(4.0)
    # Everything in the overflow bucket saturates at the last bound.
    assert M.estimate_quantiles((1.0,), (0, 5))[0] == pytest.approx(1.0)
    # Empty histograms have no quantiles.
    assert M.estimate_quantiles((1.0, 2.0), (0, 0, 0)) is None


def test_exact_boundary_samples_quantile_at_the_bound():
    # The bucket-edge interpolation fix: a sample sitting exactly ON a
    # bucket bound must not smear to the bucket midpoint.  One
    # observation at 2.0 under buckets (1, 2, 4) used to report
    # p50=1.5/p99=1.99; every quantile of a single-sample bucket is now
    # its upper bound.
    h = M.MetricsRegistry().histogram("edge_it", buckets=(1.0, 2.0, 4.0))
    h.observe(2.0)
    q = h.labels().quantiles()
    assert q == {"p50": 2.0, "p95": 2.0, "p99": 2.0}
    # Multi-sample buckets keep interpolating BETWEEN sample ranks —
    # but never below the first rank's position.
    qs = M.estimate_quantiles((1.0, 2.0, 4.0), (0, 5, 0, 0),
                              (0.01, 0.5, 1.0))
    assert qs[0] == pytest.approx(1.2)  # first of 5 ranks, not lo+eps
    assert qs[1] == pytest.approx(1.5)
    assert qs[2] == pytest.approx(2.0)


def test_registry_reset_for_tests_zeroes_without_dropping_series():
    reg = M.MetricsRegistry()
    c = reg.counter("r_total", labels=("k",))
    g = reg.gauge("r_gauge")
    h = reg.histogram("r_seconds", buckets=(1.0,))
    c.labels("a").inc(5)
    g.set(7)
    h.observe(0.5)
    reg.reset_for_tests()
    # Values are zeroed...
    assert c.labels("a").value == 0
    assert g.value == 0
    assert h.count == 0 and h.sum == 0.0
    assert h.labels().quantiles() is None
    # ...but registrations and labelled children survive (the module
    # constants stay bound to live series).
    assert reg.get("r_total") is c
    assert ("a",) in dict(c.children())
    c.labels("a").inc()
    assert c.labels("a").value == 1
    # The module-level helper covers the process-wide instances.
    M.SERVE_SHED.inc(2)
    M.EVENTS.emit("x.y")
    M.reset_for_tests()
    assert M.SERVE_SHED.value == 0
    assert len(M.EVENTS) == 0


def test_all_zero_count_histogram_has_no_quantiles():
    # An all-zero-count histogram has no distribution to interpolate:
    # estimate_quantiles must return None (not garbage like 0.0 or the
    # first bound) through every consumer layer.
    assert M.estimate_quantiles((0.5, 1.0, 2.0), np.zeros(4)) is None
    assert M.estimate_quantiles((0.5,), (0, 0), qs=(0.0, 0.5, 1.0)) is None
    reg = M.MetricsRegistry()
    h = reg.histogram("lat_seconds", buckets=(0.1, 1.0), labels=("peer",))
    h.labels("a")  # registered series, zero observations
    assert h.labels("a").quantiles() is None
    snap = reg.snapshot()["lat_seconds"]["values"]["a"]
    assert snap["count"] == 0
    assert not any(k.startswith("p") for k in snap)  # no p50/p95/p99 keys
    # ...and quantiles appear as soon as one observation lands.
    h.labels("a").observe(0.05)
    assert h.labels("a").quantiles()["p50"] > 0.0


def test_snapshot_histograms_carry_estimated_quantiles():
    reg = M.MetricsRegistry()
    h = reg.histogram("d_seconds", buckets=(0.1, 1.0, 10.0))
    empty = reg.snapshot()["d_seconds"]["values"][""]
    assert "p50" not in empty  # no estimates until data exists
    for _ in range(10):
        h.observe(0.05)
    h.observe(5.0)
    snap = reg.snapshot()["d_seconds"]["values"][""]
    assert 0.0 < snap["p50"] <= 0.1
    assert 1.0 < snap["p99"] <= 10.0
    assert snap["p50"] <= snap["p95"] <= snap["p99"]
    json.dumps(snap)  # still a JSON-clean artifact


# ---------------------------------------------------------------------------
# SrChannel transport counters under loss
# ---------------------------------------------------------------------------


def test_sr_channel_counters_under_lossy_link():
    from freedm_tpu.dcn.protocol import SrChannel
    from freedm_tpu.runtime.messages import ModuleMessage

    base = {
        n: M.REGISTRY.get(n).value
        for n in ("dcn_sends_total", "dcn_retransmits_total", "dcn_acks_total",
                  "dcn_out_of_window_drops_total")
    }
    rtt_base = M.DCN_ACK_RTT.count
    a = SrChannel("hostB:2", resend_time_s=0.05, ttl_s=60.0, src_uuid="hostA:1")
    b = SrChannel("hostA:1", resend_time_s=0.05, ttl_s=60.0, src_uuid="hostB:2")
    now = 0.0
    for i in range(5):
        a.send(ModuleMessage("lb", "draft_request", {"i": i}, source="hostA:1"), now)
    delivered = []
    for step in range(60):
        frames = a.poll(now)
        if step % 2 == 1:
            # Deliver only on odd steps: every even-step emission
            # (including the very first) is a datagram the "wire" ate —
            # the sender must retransmit before anything arrives.
            delivered += b.accept_frames(frames, now)
            # Duplicate delivery exercises the out-of-window drop path.
            b.accept_frames([f for f in frames if f.msg is not None], now)
            a.accept_frames(b.poll(now), now)
        now += 0.06
        if len(delivered) == 5 and a.outstanding == 0:
            break
    assert [m.payload["i"] for m in delivered] == [0, 1, 2, 3, 4]
    assert M.REGISTRY.get("dcn_sends_total").value == base["dcn_sends_total"] + 5
    assert M.REGISTRY.get("dcn_retransmits_total").value > base["dcn_retransmits_total"]
    assert M.REGISTRY.get("dcn_acks_total").value >= base["dcn_acks_total"] + 5
    assert (
        M.REGISTRY.get("dcn_out_of_window_drops_total").value
        > base["dcn_out_of_window_drops_total"]
    )
    assert M.DCN_ACK_RTT.count >= rtt_base + 5
    assert M.DCN_OUTSTANDING.labels("hostB:2").value == 0


# ---------------------------------------------------------------------------
# event journal
# ---------------------------------------------------------------------------


def test_journal_tail_and_memory_ring():
    j = M.JsonlEventJournal(capacity=4)
    for i in range(10):
        j.emit("tick", i=i)
    assert len(j) == 4  # bounded ring
    assert [e["i"] for e in j.tail(2)] == [8, 9]
    assert all(e["event"] == "tick" and "ts" in e for e in j.tail(10))


def test_journal_file_append_and_rotation(tmp_path):
    path = tmp_path / "events.jsonl"
    j = M.JsonlEventJournal(capacity=64)
    j.open(str(path), max_bytes=600)
    for i in range(40):
        j.emit("soak.tick", i=i, detail="x" * 10)
    j.close()
    assert (tmp_path / "events.jsonl.1").exists(), "rotation never happened"
    # Every surviving line parses; the newest file continues the stream.
    recs = [json.loads(l) for l in path.read_text().splitlines()]
    assert recs and recs[-1]["i"] == 39
    older = [
        json.loads(l)
        for l in (tmp_path / "events.jsonl.1").read_text().splitlines()
    ]
    assert older and older[-1]["i"] < 39


def test_journal_since_cursor_resumes_and_observes_gaps():
    # The /events?since= seam: every record carries a monotone seq,
    # since(cursor) returns strictly-newer records oldest first, and a
    # cursor that slept through ring eviction can SEE the gap (the first
    # returned seq jumps past cursor+1) instead of silently losing data.
    j = M.JsonlEventJournal(capacity=8)
    for i in range(5):
        j.emit("tick", i=i)
    cursor = j.tail(1)[-1]["seq"]
    assert j.since(cursor) == []
    j.emit("tick", i=5)
    j.emit("tick", i=6)
    out = j.since(cursor)
    assert [e["i"] for e in out] == [5, 6]
    assert [e["seq"] for e in out] == [cursor + 1, cursor + 2]
    # Overflow the capacity-8 ring: the stale cursor's next read starts
    # past the eviction horizon, and the seq jump exposes the gap.
    for i in range(20):
        j.emit("tick", i=100 + i)
    out = j.since(cursor)
    assert len(out) == 8 and out[0]["seq"] > cursor + 1


def test_events_since_route_serves_cursor_pagination():
    j = M.JsonlEventJournal(capacity=64)
    for i in range(6):
        j.emit("cursor.tick", i=i)
    cursor = j.tail(4)[0]["seq"]
    srv = M.MetricsServer(journal=j, port=0).start()
    try:
        body = _scrape(srv.port, f"/events?since={cursor}")
        recs = [json.loads(l) for l in body.splitlines()]
        assert [r["i"] for r in recs] == [3, 4, 5]
        assert all(r["seq"] > cursor for r in recs)
        # Resuming from the last seen seq returns nothing new...
        assert _scrape(srv.port, f"/events?since={recs[-1]['seq']}") == ""
        # ...and ?n= tail mode is unchanged alongside the cursor mode.
        tail = _scrape(srv.port, "/events?n=2")
        assert [json.loads(l)["i"] for l in tail.splitlines()] == [4, 5]
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# exposition endpoint
# ---------------------------------------------------------------------------


def _scrape(port, path="/metrics"):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        return r.read().decode()


def test_metrics_server_serves_parseable_prometheus_text():
    M.EVENTS.emit("test.marker", origin="test_metrics")
    srv = M.MetricsServer(port=0).start()
    try:
        text = _scrape(srv.port)
        # The catalogue names the acceptance criteria require, present
        # even before any traffic (pre-registered at import).
        for needle in (
            "dcn_retransmits_total",
            'dcn_ack_rtt_seconds_bucket{le="+Inf"}',
            "pf_newton_iterations",
            "broker_phase_overruns_total",
            "broker_rounds_total",
        ):
            assert needle in text, needle
        # Parseable: every sample line is "name{labels} value".
        for line in text.splitlines():
            if not line or line.startswith("#"):
                continue
            name_part, value = line.rsplit(" ", 1)
            assert name_part
            float(value)
        events = _scrape(srv.port, "/events?n=500")
        assert any(
            json.loads(l).get("event") == "test.marker"
            for l in events.splitlines()
        )
        with pytest.raises(urllib.error.HTTPError) as err:
            _scrape(srv.port, "/nope")
        assert err.value.code == 404
    finally:
        srv.stop()


def test_cli_metrics_port_scrape_end_to_end(tmp_path):
    """`--metrics-port 0` on a config-driven runtime: the ephemeral
    endpoint serves the DCN/solver/broker vocabulary, the round roll-ups
    agree with the telemetry ring, and the journal lands on disk."""
    from test_checkpoint import write_rig

    from freedm_tpu.cli import build_runtime
    from freedm_tpu.core.config import GlobalConfig

    cfg = write_rig(tmp_path)
    cfg = GlobalConfig(**{
        **cfg.__dict__,
        "metrics_port": 0,
        "events_log": str(tmp_path / "events.jsonl"),
    })
    rounds_before = M.BROKER_ROUNDS.value
    rt = build_runtime(cfg).start()
    try:
        rt.broker.run(n_rounds=4)
        assert rt.metrics_server is not None
        text = _scrape(rt.metrics_server.port)
        for needle in (
            "dcn_retransmits_total",
            'dcn_ack_rtt_seconds_bucket{le="0.06"}',
            "pf_newton_iterations",
            "broker_phase_overruns_total",
            "checkpoint_saves_total",
        ):
            assert needle in text, needle
        assert M.BROKER_ROUNDS.value == rounds_before + 4
        # Registry gauges come FROM the telemetry ring record — the two
        # surfaces cannot disagree.
        t = rt.telemetry.telemetry.summary()
        assert M.FLEET_GROUPS.value == t["last_n_groups"]
        assert f"fleet_groups {int(t['last_n_groups'])}" in text
        # checkpoint.save events were journaled in memory and on disk.
        assert any(e["event"] == "checkpoint.save" for e in M.EVENTS.tail(200))
        on_disk = (tmp_path / "events.jsonl").read_text()
        assert "checkpoint.save" in on_disk
    finally:
        rt.stop()


# ---------------------------------------------------------------------------
# satellites: q_ctrl restore validation + status-masked oracle
# ---------------------------------------------------------------------------


def test_checkpoint_restore_rejects_wrong_mesh_q_ctrl_shape():
    from freedm_tpu.devices.manager import DeviceManager
    from freedm_tpu.runtime import checkpoint as ckpt
    from freedm_tpu.runtime.broker import Broker
    from freedm_tpu.runtime.fleet import Fleet, NodeHandle
    from freedm_tpu.runtime.module import DgiModule

    class StubMesh(DgiModule):
        """Shape contract of a MeshFleetModule without building a mesh."""

        name = "mesh"
        n_scenarios = 8
        q_ctrl_shape = (8, 9, 3)
        _restore_q_ctrl = None
        _prev_loss = None
        rounds = 0

        def run_phase(self, ctx):
            pass

    fleet = Fleet([NodeHandle("hostA:1", DeviceManager())])
    broker = Broker()
    broker.register_module(StubMesh(), 0)
    state = {
        "version": ckpt.FORMAT_VERSION,
        "round_index": 3,
        "nodes": ["hostA:1"],
        "mesh": {"q_ctrl": np.zeros((4, 9, 3)).tolist(), "prev_loss": None,
                 "rounds": 3},
    }
    with pytest.raises(ValueError, match="q_ctrl"):
        ckpt.restore_state(state, broker, fleet)
    rejected = [
        e for e in M.EVENTS.tail(50)
        if e["event"] == "checkpoint.restore_rejected"
    ]
    assert rejected and rejected[-1]["reason"] == "q_ctrl_shape"
    assert rejected[-1]["expected"] == [8, 9, 3]
    # The matching shape restores cleanly.
    state["mesh"]["q_ctrl"] = np.zeros((8, 9, 3)).tolist()
    ckpt.restore_state(state, broker, fleet)
    assert broker._by_name["mesh"].module._restore_q_ctrl.shape == (8, 9, 3)


def test_true_mismatch_oracle_accepts_status_mask():
    import jax.numpy as jnp

    from freedm_tpu.grid.cases import synthetic_mesh
    from freedm_tpu.pf.newton import make_newton_solver
    from freedm_tpu.pf.krylov import true_mismatch

    sys_ = synthetic_mesh(30, seed=1, load_mw=5.0, chord_frac=1.0)
    solve, _ = make_newton_solver(sys_, dtype=jnp.float64)
    status = np.ones(sys_.n_branch)
    status[sys_.n_bus] = 0.0  # one chord out — never islands the ring
    r = solve(status=jnp.asarray(status))
    assert bool(r.converged)
    # The masked oracle certifies the outage solve; the base-topology
    # oracle (old behavior) sees the missing branch as a real residual.
    assert true_mismatch(sys_, r, status=status) < 1e-7
    assert true_mismatch(sys_, r) > 1e-4
