"""Multi-process federation tests (VERDICT r3 item 1).

The reference DGI is N processes cooperating over UDP: GM Invite/Accept
group formation (``Broker/src/gm/GroupManagement.cpp:710-813``), LB
draft migrations (``lb/LoadBalance.cpp:609-956``), SC counting the
Accepts crossing its cut (``sc/StateCollection.cpp:539-558``).  These
tests run TWO independent broker stacks — first in-process over real
UDP sockets (so link reliability can be flipped live), then as two
``python -m freedm_tpu --federate`` subprocesses — and check:

- the processes form one federation group (invitation election);
- power migrates across the process boundary (slice draft auction),
  with the conserved total intact and Accepts counted by SC;
- a dead link splits the group, a restored link re-merges it.
"""

import json
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

from freedm_tpu.core.config import Timings
from freedm_tpu.dcn.endpoint import UdpEndpoint
from freedm_tpu.devices.adapters.fake import FakeAdapter
from freedm_tpu.devices.manager import DeviceManager
from freedm_tpu.runtime import Fleet, NodeHandle, build_broker
from freedm_tpu.runtime.federation import Federation, process_priority

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_udp_ports(n):
    socks = [socket.socket(socket.AF_INET, socket.SOCK_DGRAM) for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


class Slice:
    """One process-equivalent: endpoint + federation + fleet + broker."""

    def __init__(self, port, peer_ports, generation=0.0, drain=0.0):
        self.uuid = f"127.0.0.1:{port}"
        self.adapter = FakeAdapter(
            {
                ("SST", "gateway"): 0.0,
                ("DRER", "generation"): generation,
                ("LOAD", "drain"): drain,
            }
        )
        manager = DeviceManager()
        manager.add_device("SST", "Sst", self.adapter)
        manager.add_device("DRER", "Drer", self.adapter)
        manager.add_device("LOAD", "Load", self.adapter)
        self.adapter.reveal_devices()
        self.fleet = Fleet([NodeHandle(self.uuid, manager)], migration_step=1.0)
        self.endpoint = UdpEndpoint(self.uuid, bind=("127.0.0.1", port))
        peers = {f"127.0.0.1:{p}": ("127.0.0.1", p) for p in peer_ports}
        self.fed = Federation(self.endpoint, peers, migration_step=1.0)
        self.broker = build_broker(self.fleet, federation=self.fed)
        self.endpoint.sink = self.broker.deliver
        self.endpoint.start()

    def gateway(self):
        return self.adapter.get_state("SST", "gateway")

    def stop(self):
        self.endpoint.stop()


def run_until(slices, cond, timeout_s=20.0, sleep_s=0.01):
    """Interleave rounds across the slices until ``cond()`` holds."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        for s in slices:
            s.broker.run_round()
        if cond():
            return True
        time.sleep(sleep_s)
    return cond()


@pytest.fixture
def pair():
    pa, pb = free_udp_ports(2)
    a = Slice(pa, [pb], generation=30.0, drain=10.0)  # +20 surplus
    b = Slice(pb, [pa], drain=20.0)  # -20 deficit
    yield a, b
    a.stop()
    b.stop()


def test_two_slices_form_one_group(pair):
    a, b = pair
    ok = run_until(
        [a, b],
        lambda: a.fed.members == b.fed.members == {a.uuid, b.uuid}
        and a.fed.leader == b.fed.leader,
    )
    assert ok, (a.fed.view(), b.fed.view())
    # The leader is the higher-hash process (reference priority rule).
    want = max([a.uuid, b.uuid], key=process_priority)
    assert a.fed.leader == want
    # Exactly one side is the coordinator.
    assert a.fed.is_coordinator != b.fed.is_coordinator


def test_power_migrates_across_processes(pair):
    a, b = pair
    assert run_until(
        [a, b], lambda: a.fed.members == {a.uuid, b.uuid} == b.fed.members
    )
    # Drafts run until both slices are inside the ±step band:
    # A exports its +20 surplus, B absorbs its -20 deficit.
    ok = run_until(
        [a, b],
        lambda: a.gateway() >= 19.0
        and b.gateway() <= -19.0
        and a.fed.fed_intransit == 0,
    )
    assert ok, (a.gateway(), b.gateway(), a.fed.fed_intransit)
    assert a.fed.fed_migrations >= 19
    # Conservation: what A exported B imported (plus any in-flight).
    total = a.gateway() + b.gateway() + a.fed.fed_intransit + b.fed.fed_intransit
    assert abs(total) < 1e-6
    # SC on the supply side counted the cut-crossing Accepts (the
    # demand slice's DraftAccepts arrive on "lb" where SC subscribes).
    assert a.broker._by_name["sc"].module.total_accepts >= 19
    # The federated snapshot covers both slices and, once the drafts
    # settle (each slice's report reflects the same quiescent cut),
    # sums to the conserved total.
    def settled():
        fc = a.broker.shared.get("fed_collected")
        return (
            fc is not None
            and fc["n_slices"] == 2
            and abs(fc["gateway"] + fc["intransit"]) < 1e-6
        )

    assert run_until([a, b], settled), a.broker.shared.get("fed_collected")


def test_link_drop_splits_then_remerges(pair):
    a, b = pair
    assert run_until(
        [a, b], lambda: a.fed.members == {a.uuid, b.uuid} == b.fed.members
    )
    # Kill the link in both directions (reliability=0, the reference's
    # CUSTOMNETWORK loss injection).
    for s, other in ((a, b), (b, a)):
        s.endpoint.incoming_reliability = 0
        s.endpoint._peers[other.uuid].reliability = 0
    ok = run_until(
        [a, b],
        lambda: a.fed.members == {a.uuid} and b.fed.members == {b.uuid},
        timeout_s=30.0,
    )
    assert ok, (a.fed.view(), b.fed.view())
    # Both sides lead their own singleton group now.
    assert a.fed.is_coordinator and b.fed.is_coordinator
    # Restore the link: the coordinators rediscover each other via AYC
    # and merge back into one group.
    for s, other in ((a, b), (b, a)):
        s.endpoint.incoming_reliability = 100
        s.endpoint._peers[other.uuid].reliability = 100
    ok = run_until(
        [a, b],
        lambda: a.fed.members == {a.uuid, b.uuid} == b.fed.members
        and a.fed.leader == b.fed.leader,
        timeout_s=30.0,
    )
    assert ok, (a.fed.view(), b.fed.view())


def test_late_accept_after_rollback_conserves_power(pair):
    """An accept that lands after the exporter's timeout rollback must
    re-apply the export (the importer already applied its -step), or
    the federated total drifts by one step per loss-delayed accept."""
    from freedm_tpu.runtime.messages import ModuleMessage

    a, b = pair
    assert run_until(
        [a, b], lambda: a.fed.members == {a.uuid, b.uuid} == b.fed.members
    )
    a.broker.run_round()  # ensure readings exist for _pick_node
    before = a.fed._ensure_delta(1).copy()
    a.fed._fed_delta = before.copy()
    late = ModuleMessage("lb", "accept", {"amount": 1.0}, source=b.uuid)
    a.fed.handle_lb(late, 1)  # no pending select for b -> late path
    assert a.fed._fed_delta[0] == before[0] + 1.0
    assert a.fed.fed_migrations >= 1


# ---------------------------------------------------------------------------
# Subprocess e2e: two `python -m freedm_tpu --federate` processes
# ---------------------------------------------------------------------------


def _write_fed_configs(tmp_path, ports, me, peer, timings_overrides=None):
    """Reference-style config set for one federated process.

    ``timings_overrides`` patches fields of the serialized timings.cfg
    (e.g. small realtime phase budgets) — callers must not hand-write
    the file, or later helper calls would overwrite it with defaults."""
    from freedm_tpu.devices.schema import DEFAULT_TYPES
    import dataclasses

    lines = ["<root>"]
    for t in DEFAULT_TYPES:
        lines.append(f"  <deviceType><id>{t.id}</id>")
        for s in t.states:
            lines.append(f"    <state>{s}</state>")
        for c in t.commands:
            lines.append(f"    <command>{c}</command>")
        lines.append("  </deviceType>")
    lines.append("</root>")
    (tmp_path / "device.xml").write_text("\n".join(lines))
    tvals = {
        f.name: getattr(Timings(), f.name) for f in dataclasses.fields(Timings)
    }
    tvals.update(timings_overrides or {})
    (tmp_path / "timings.cfg").write_text(
        "\n".join(f"{k.upper()} = {v}" for k, v in tvals.items())
    )
    # Both slices' adapters in ONE shared adapter.xml; the owner
    # attribute routes them, non-local owners are skipped in federate
    # mode.  Seeded fake devices: A surplus +20, B deficit -20.
    seeds = {
        f"127.0.0.1:{ports[0]}": [("DRER", "Drer", "generation", 30.0),
                                  ("LOAD", "Load", "drain", 10.0),
                                  ("SST", "Sst", "gateway", 0.0)],
        f"127.0.0.1:{ports[1]}": [("LOAD", "Load", "drain", 20.0),
                                  ("SST", "Sst", "gateway", 0.0)],
    }
    al = ["<root>"]
    for uuid, devs in seeds.items():
        al.append(f'  <adapter name="rig-{uuid.split(":")[1]}" type="fake" owner="{uuid}">')
        al.append("    <state>")
        for i, (dev, typ, sig, val) in enumerate(devs):
            al.append(
                f'      <entry index="{i + 1}" value="{val}"><type>{typ}</type>'
                f"<device>{dev}</device><signal>{sig}</signal></entry>"
            )
        al.append("    </state>")
        al.append("  </adapter>")
    al.append("</root>")
    (tmp_path / "adapter.xml").write_text("\n".join(al))
    cfg = tmp_path / f"freedm_{me}.cfg"
    cfg.write_text(
        f"hostname = 127.0.0.1\nport = {me}\nfederate = yes\n"
        f"add-host = 127.0.0.1:{peer}\nmigration-step = 1\n"
        f"device-config = {tmp_path}/device.xml\n"
        f"adapter-config = {tmp_path}/adapter.xml\n"
        f"timings-config = {tmp_path}/timings.cfg\n"
    )
    return cfg


class _Proc:
    def __init__(self, cfg, extra=()):
        self.cfg = cfg
        self.extra = list(extra)
        self.lines = []
        self.proc = None
        self.start()

    def start(self):
        import threading

        env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "freedm_tpu", "-c", str(self.cfg),
             "--summary-every", "25"] + self.extra,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env, text=True,
        )
        self._t = threading.Thread(target=self._pump, daemon=True)
        self._t.start()
        # stderr must drain too (operator tables log there), or the
        # child blocks on a full pipe.
        self._te = threading.Thread(
            target=lambda: [None for _ in self.proc.stderr], daemon=True
        )
        self._te.start()

    def _pump(self):
        for line in self.proc.stdout:
            if line.startswith("{"):
                try:
                    self.lines.append(json.loads(line))
                except ValueError:
                    pass

    def last(self):
        return self.lines[-1] if self.lines else {}

    def wait_for(self, cond, timeout_s=60.0):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if cond(self.last()):
                return True
            time.sleep(0.1)
        return False

    def kill(self):
        if self.proc and self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait(timeout=5)


#: Bounded attempts for the subprocess e2e (the PR5 fleet-test
#: discipline): multi-process + wall-clock regrouping is inherently
#: load-sensitive, so a failed run retries on fresh ports — but never
#: more than this many attempts total.
E2E_ATTEMPTS = 2


def test_federated_processes_e2e(tmp_path):
    """Two real freedm_tpu processes over real UDP: one group, power
    migrated, a killed peer splits the group, a restart re-merges it.

    Readiness-polled end to end (no fixed round counts or sleeps):
    every phase polls its own condition under a bounded deadline, a
    child that EXITS mid-phase fails the attempt immediately instead
    of burning the deadline, and the whole scenario retries once on
    fresh ports — the same bounded-retry pattern the PR5 tracing
    fleet test uses for multi-process wall-clock scenarios."""
    last = None
    for attempt in range(E2E_ATTEMPTS):
        try:
            _assert_federated_processes_e2e(tmp_path / f"attempt{attempt}")
            return
        except AssertionError as e:
            last = e
    raise last


def _assert_federated_processes_e2e(workdir):
    workdir.mkdir(parents=True, exist_ok=True)
    ports = free_udp_ports(2)
    cfg_a = _write_fed_configs(workdir, ports, ports[0], ports[1])
    cfg_b = _write_fed_configs(workdir, ports, ports[1], ports[0])
    # --summary-every 5: the readiness conditions below poll the round
    # summaries, so the summary cadence IS the polling resolution (25
    # free-running rounds could outlive a phase deadline under load).
    a = _Proc(cfg_a, extra=["--summary-every", "5"])
    b = _Proc(cfg_b, extra=["--summary-every", "5"])

    def alive_wait(proc, other, cond, timeout_s):
        """wait_for that fails FAST when either child exits (a dead
        child can never satisfy the condition — burning the rest of
        the deadline just converts a crash into a timeout)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if cond(proc.last()):
                return True
            if proc.proc.poll() is not None:
                return False
            if other is not None and other.proc.poll() is not None:
                return False
            time.sleep(0.1)
        return cond(proc.last())

    try:
        # Phase 1: federation forms and power flows A→B.
        ok = alive_wait(
            a, b,
            lambda l: l.get("fed_members") == 2
            and l.get("gateway_total", 0) >= 5.0,
            timeout_s=90.0,
        )
        assert ok, (a.last(), b.last(), a.proc.poll(), b.proc.poll())
        assert alive_wait(b, a, lambda l: l.get("fed_members") == 2,
                          timeout_s=30.0), (b.last(), b.proc.poll())
        leader_before = a.last().get("fed_leader")
        # Phase 2: kill B — A's group must shrink to itself.
        b.kill()
        assert alive_wait(a, None, lambda l: l.get("fed_members") == 1,
                          timeout_s=90.0), (a.last(), a.proc.poll())
        # Phase 3: restart B — the groups re-merge.
        b.lines.clear()
        b.start()
        assert alive_wait(a, b, lambda l: l.get("fed_members") == 2,
                          timeout_s=90.0), (a.last(), b.proc.poll())
        assert alive_wait(b, a, lambda l: l.get("fed_members") == 2,
                          timeout_s=30.0), (b.last(), b.proc.poll())
        assert b.last().get("fed_leader") == a.last().get("fed_leader")
        assert leader_before is not None
    finally:
        a.kill()
        b.kill()


def test_three_slices_form_one_group_and_balance():
    """Three processes federate into one group (transitive invites:
    the winning coordinator absorbs both others) and the draft auction
    serves two demand slices from one supply."""
    pa, pb, pc = free_udp_ports(3)
    a = Slice(pa, [pb, pc], generation=30.0, drain=10.0)  # +20
    b = Slice(pb, [pa, pc], drain=10.0)  # -10
    c = Slice(pc, [pa, pb], drain=10.0)  # -10
    slices = [a, b, c]
    try:
        all_uuids = {a.uuid, b.uuid, c.uuid}
        ok = run_until(
            slices,
            lambda: all(s.fed.members == all_uuids for s in slices)
            and len({s.fed.leader for s in slices}) == 1,
            timeout_s=30.0,
        )
        assert ok, [s.fed.view() for s in slices]
        want = max(all_uuids, key=process_priority)
        assert a.fed.leader == want
        ok = run_until(
            slices,
            lambda: a.gateway() >= 19.0
            and b.gateway() <= -9.0
            and c.gateway() <= -9.0
            and a.fed.fed_intransit == 0,
            timeout_s=40.0,
        )
        assert ok, (a.gateway(), b.gateway(), c.gateway())
        total = sum(s.gateway() for s in slices)
        assert abs(total) < 1e-6
    finally:
        for s in slices:
            s.stop()


def test_federation_survives_lossy_links():
    """30% datagram loss in every direction: the SR transport's resends
    carry the election and the draft auction through, and the
    late-accept/rollback reconciliation keeps the total conserved."""
    pa, pb = free_udp_ports(2)
    a = Slice(pa, [pb], generation=30.0, drain=10.0)
    b = Slice(pb, [pa], drain=20.0)
    for s, other, seed in ((a, b, 1), (b, a, 2)):
        s.endpoint._rng = np.random.default_rng(seed)
        s.endpoint.incoming_reliability = 70
        s.endpoint._peers[other.uuid].reliability = 70
    try:
        assert run_until(
            [a, b],
            lambda: a.fed.members == {a.uuid, b.uuid} == b.fed.members,
            timeout_s=40.0,
        ), (a.fed.view(), b.fed.view())
        ok = run_until(
            [a, b],
            lambda: a.gateway() >= 19.0
            and b.gateway() <= -19.0
            and a.fed.fed_intransit == 0
            and b.fed.fed_intransit == 0,
            timeout_s=60.0,
            sleep_s=0.02,
        )
        assert ok, (a.gateway(), b.gateway(), a.fed.fed_rollbacks)
        # Conservation held through every loss/rollback interleaving.
        total = a.gateway() + b.gateway()
        assert abs(total) < 1e-6
    finally:
        a.stop()
        b.stop()


def test_federated_realtime_with_clock_sync_e2e(tmp_path):
    """The flagship deployment shape, all pieces at once: two federated
    CLI processes in REALTIME mode on one host, phase budgets honored,
    clock synchronizer attached, group formed, power migrated."""
    ports = free_udp_ports(2)
    # Small realtime budgets: gm 80 + sc 40 + lb 120 = 240 ms rounds.
    small = dict(gm_phase_time=80, sc_phase_time=40, lb_phase_time=120,
                 vvc_phase_time=0)
    cfg_a = _write_fed_configs(
        tmp_path, ports, ports[0], ports[1], timings_overrides=small
    )
    cfg_b = _write_fed_configs(
        tmp_path, ports, ports[1], ports[0], timings_overrides=small
    )
    procs = []
    try:
        for cfg in (cfg_a, cfg_b):
            procs.append(_Proc(cfg, extra=["--realtime"]))
        # _Proc summarizes every 25 rounds; at 240 ms realtime rounds
        # that is one summary per ~6 s — fine within the deadline.
        ok_a = procs[0].wait_for(
            lambda l: l.get("fed_members") == 2 and l.get("gateway_total", 0) >= 3.0,
            timeout_s=120.0,
        )
        assert ok_a, (procs[0].last(), procs[1].last())
        assert procs[1].wait_for(
            lambda l: l.get("fed_members") == 2, timeout_s=60.0
        )
        # Realtime honored: round-time p50 tracks the 240 ms budget
        # (free-running would report ~ms).
        p50 = procs[0].last().get("round_ms_p50")
        assert p50 is not None and p50 >= 200.0, procs[0].last()
        assert procs[0].last().get("fed_leader") == procs[1].last().get("fed_leader")
    finally:
        for p in procs:
            p.kill()


def test_federated_vvc_master_drives_slave_devices():
    """The reference's master/slave VVC (GradientMessage -> vvc_slave,
    Broker_s1..s3): a member slice ships its Pload readings and Sst rows
    to the coordinator, whose gradient step covers the union of rows and
    ships the member rows back; the slave actuates them locally."""
    from freedm_tpu.grid import cases
    from freedm_tpu.runtime import VvcModule
    from freedm_tpu.runtime.fleet import build_broker as _bb

    feeder = cases.vvc_9bus()
    pa, pb = free_udp_ports(2)
    slices = {}
    for port, peer, rows in ((pa, pb, (2, 3)), (pb, pa, (4, 5, 6))):
        uuid = f"127.0.0.1:{port}"
        seeds = {}
        names = {}
        for row in rows:
            for pi, ph in enumerate("abc"):
                seeds[(f"Q{row}_{ph}", "gateway")] = 0.0
                names[f"Q{row}_{ph}"] = f"Sst_{ph}"
        fake = FakeAdapter(seeds)
        manager = DeviceManager()
        for name, tname in names.items():
            manager.add_device(name, tname, fake)
        fake.reveal_devices()
        fleet = Fleet([NodeHandle(uuid, manager)], migration_step=1.0)
        endpoint = UdpEndpoint(uuid, bind=("127.0.0.1", port))
        fed = Federation(
            endpoint, {f"127.0.0.1:{peer}": ("127.0.0.1", peer)},
            migration_step=1.0,
        )
        vvc = VvcModule(fleet, feeder, federation=fed)
        broker = _bb(fleet, federation=fed, extra_modules=[vvc])
        endpoint.sink = broker.deliver
        endpoint.start()
        slices[uuid] = type("S", (), dict(
            uuid=uuid, fed=fed, broker=broker, vvc=vvc, fake=fake,
            endpoint=endpoint, rows=rows,
        ))()
    a, b = slices.values()
    try:
        assert run_until(
            list(slices.values()),
            lambda: a.fed.members == {a.uuid, b.uuid} == b.fed.members,
        )
        master, slave = (a, b) if a.fed.is_coordinator else (b, a)
        ok = run_until(
            list(slices.values()),
            lambda: slave.vvc.slave_rounds > 2
            and any(
                slave.fake.get_state(f"Q{row}_{ph}", "gateway") != 0.0
                for row in slave.rows
                for ph in "abc"
            ),
            timeout_s=30.0,
        )
        assert ok, (master.vvc.rounds, slave.vvc.slave_rounds)
        # The master's accepted q covers BOTH slices' rows.
        q = np.asarray(master.vvc.q_kvar)
        assert np.abs(q[list(master.rows)]).sum() > 0
        assert np.abs(q[list(slave.rows)]).sum() > 0
        # Settle: one slave-only round applies its latest received set
        # (the hand-off lags by the in-flight message, by design), after
        # which the devices hold exactly what the master shipped.
        slave.broker.run_round()
        sets = {
            (int(r), int(p)): float(v)
            for r, p, v in (slave.fed.vvc_take_setpoints() or [])
        }
        assert sets, "slave never received setpoints"
        for (row, pi), want in sets.items():
            ph = "abc"[pi]
            assert slave.fake.get_state(
                f"Q{row}_{ph}", "gateway"
            ) == pytest.approx(want, rel=1e-6)
        # The master saw descent with the full control mask.
        assert master.vvc.improved_rounds >= 1
        # Once enslaved, the member never runs its own gradient step
        # again (it legitimately ran as its own master pre-federation).
        before = slave.vvc.rounds
        run_until([master, slave], lambda: False, timeout_s=0.5)
        assert slave.vvc.rounds == before
        assert slave.vvc.slave_rounds > 3
    finally:
        for s in slices.values():
            s.endpoint.stop()


def test_member_falls_back_to_standalone_under_vvc_less_master():
    """A coordinator that runs no VVC module must not silently disable
    volt-var on its members: with no fresh setpoints arriving, the
    member keeps running its own gradient loop and actuating locally."""
    from freedm_tpu.grid import cases
    from freedm_tpu.runtime import VvcModule
    from freedm_tpu.runtime.fleet import build_broker as _bb

    feeder = cases.vvc_9bus()
    ports = free_udp_ports(2)
    uuids = [f"127.0.0.1:{p}" for p in ports]
    # The higher-hash uuid wins the election; give VVC to the LOSER so
    # the coordinator is vvc-less.
    winner = max(uuids, key=process_priority)
    slices = []
    for port, uuid in zip(ports, uuids):
        peer_port = ports[1] if port == ports[0] else ports[0]
        has_vvc = uuid != winner
        seeds, names = {}, {}
        if has_vvc:
            for row in (4, 5):
                for ph in "abc":
                    seeds[(f"Q{row}_{ph}", "gateway")] = 0.0
                    names[f"Q{row}_{ph}"] = f"Sst_{ph}"
        fake = FakeAdapter(seeds)
        manager = DeviceManager()
        for name, tname in names.items():
            manager.add_device(name, tname, fake)
        fake.reveal_devices()
        fleet = Fleet([NodeHandle(uuid, manager)], migration_step=1.0)
        endpoint = UdpEndpoint(uuid, bind=("127.0.0.1", port))
        fed = Federation(
            endpoint, {f"127.0.0.1:{peer_port}": ("127.0.0.1", peer_port)},
            migration_step=1.0,
        )
        extra = []
        vvc = None
        if has_vvc:
            vvc = VvcModule(fleet, feeder, federation=fed)
            extra.append(vvc)
        broker = _bb(fleet, federation=fed, extra_modules=extra)
        endpoint.sink = broker.deliver
        endpoint.start()
        slices.append(type("S", (), dict(
            uuid=uuid, fed=fed, broker=broker, vvc=vvc, fake=fake,
            endpoint=endpoint,
        ))())
    member = next(s for s in slices if s.vvc is not None)
    try:
        assert run_until(
            slices, lambda: all(len(s.fed.members) == 2 for s in slices)
        )
        assert not member.fed.is_coordinator
        # Grouped under a vvc-less master, the member keeps its own
        # gradient loop alive and actuates its devices.
        r0 = member.vvc.rounds
        ok = run_until(
            slices,
            lambda: member.vvc.rounds > r0 + 3
            and any(
                member.fake.get_state(f"Q{row}_{ph}", "gateway") != 0.0
                for row in (4, 5)
                for ph in "abc"
            ),
            timeout_s=20.0,
        )
        assert ok, (member.vvc.rounds, member.vvc.slave_rounds)
        assert member.vvc.slave_rounds == 0
    finally:
        for s in slices:
            s.endpoint.stop()
