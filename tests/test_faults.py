"""Fault-injection framework tests (``freedm_tpu.core.faults``):
spec parsing + typed rejection, deterministic replay, the disabled-path
cost contract, and the end-to-end injection sites — DCN drop absorbed
by the SR transport, executor crash contained to one batch, cache
corruption caught by the float64 residual verify, and the QSTS
worker-crash auto-requeue.
"""

import time

import pytest

from freedm_tpu.core import metrics as M
from freedm_tpu.core.faults import (
    FAULTS,
    FaultRegistry,
    KNOWN_POINTS,
    parse_spec,
)


@pytest.fixture(autouse=True)
def _reset_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


# ---------------------------------------------------------------------------
# spec parsing + determinism
# ---------------------------------------------------------------------------


def test_parse_spec_full_grammar():
    seed, points = parse_spec(
        "seed=7; dcn.drop_tx:0.25; serve.exec.delay:1:arg=0.05:max=3;"
        "serve.replica.kill:1:after=80:max=1"
    )
    assert seed == 7
    by = {p.name: p for p in points}
    assert by["dcn.drop_tx"].rate == 0.25
    assert by["serve.exec.delay"].arg == 0.05
    assert by["serve.exec.delay"].max_fires == 3
    assert by["serve.replica.kill"].after == 80


def test_unknown_point_and_bad_options_are_typed_errors():
    with pytest.raises(ValueError, match="unknown fault point"):
        parse_spec("dcn.drop_everything:0.5")
    with pytest.raises(ValueError, match="rate"):
        parse_spec("dcn.drop_tx:1.5")
    with pytest.raises(ValueError, match="unknown fault option"):
        parse_spec("dcn.drop_tx:0.5:frequency=2")
    with pytest.raises(ValueError, match="name:rate"):
        parse_spec("dcn.drop_tx")


def test_schedule_replays_identically():
    """The acceptance contract: a fresh registry configured with the
    SAME spec fires the identical sequence, and sequence() predicts it
    without consuming draws."""
    spec = "seed=42;dcn.drop_rx:0.3:after=2;serve.exec.crash:0.6:max=4"
    a, b = FaultRegistry(), FaultRegistry()
    a.configure(spec)
    b.configure(spec)
    for point in ("dcn.drop_rx", "serve.exec.crash"):
        predicted = a.sequence(point, 50)
        fired_a = [a.should(point) for _ in range(50)]
        fired_b = [b.should(point) for _ in range(50)]
        assert predicted == fired_a == fired_b
    # A different seed produces a different schedule.
    c = FaultRegistry().configure(spec.replace("seed=42", "seed=43"))
    assert [c.should("dcn.drop_rx") for _ in range(50)] != \
        [FaultRegistry().configure(spec).should("dcn.drop_rx")
         for _ in range(50)]


def test_explicit_zero_arg_is_honored():
    """`arg=0` is a configured value, not a fall-through to the site
    default (a zero-magnitude control run must actually be zero)."""
    r = FaultRegistry().configure("seed=1;serve.cache.corrupt:1:arg=0")
    assert r.arg("serve.cache.corrupt", 0.05) == 0.0
    r2 = FaultRegistry().configure("seed=1;serve.cache.corrupt:1")
    assert r2.arg("serve.cache.corrupt", 0.05) == 0.05  # unconfigured


def test_after_and_max_bound_the_fires():
    r = FaultRegistry().configure("seed=1;dcn.drop_tx:1:after=3:max=2")
    fires = [r.should("dcn.drop_tx") for _ in range(10)]
    assert fires == [False] * 3 + [True, True] + [False] * 5


def test_disabled_path_is_one_attribute_check():
    """The production contract: with no schedule configured, the
    instrumented sites pay one attribute read.  Pin the shape (enabled
    is a plain False attribute) and a generous absolute bound on the
    guard itself — not a brittle micro-benchmark, just a tripwire
    against someone putting a lock or dict probe on the disabled path."""
    assert FAULTS.enabled is False
    n = 100_000
    t0 = time.perf_counter()
    for _ in range(n):
        if FAULTS.enabled:  # the exact guard every site uses
            FAULTS.should("dcn.drop_tx")
    per_call = (time.perf_counter() - t0) / n
    assert per_call < 5e-6, f"disabled fault guard cost {per_call * 1e9:.0f} ns"
    # configure(None) / reset() return to the disabled state.
    FAULTS.configure("seed=1;dcn.drop_tx:1")
    assert FAULTS.enabled
    FAULTS.configure(None)
    assert FAULTS.enabled is False


def test_every_known_point_is_documented():
    text = open("docs/robustness.md").read()
    for name in KNOWN_POINTS:
        assert f"`{name}`" in text, f"{name} missing from docs/robustness.md"


# ---------------------------------------------------------------------------
# injection sites, end to end
# ---------------------------------------------------------------------------


def test_dcn_drop_tx_is_absorbed_by_sr_retransmits():
    """100%-for-3-fires egress drop: the SR channel's resend clock must
    deliver the message anyway, and the injected drops are counted."""
    from freedm_tpu.dcn.endpoint import UdpEndpoint
    from freedm_tpu.runtime.messages import ModuleMessage

    from test_federation import free_udp_ports

    pa, pb = free_udp_ports(2)
    got = []
    a = UdpEndpoint(f"127.0.0.1:{pa}", bind=("127.0.0.1", pa),
                    resend_time_s=0.02)
    b = UdpEndpoint(f"127.0.0.1:{pb}", bind=("127.0.0.1", pb),
                    sink=got.append, resend_time_s=0.02)
    a.connect(b.uuid, ("127.0.0.1", pb))
    b.connect(a.uuid, ("127.0.0.1", pa))
    injected_before = M.FAULTS_INJECTED.labels("dcn.drop_tx").value
    FAULTS.configure("seed=5;dcn.drop_tx:1:max=3")
    a.start()
    b.start()
    try:
        a.send(b.uuid, ModuleMessage("lb", "ping", {"n": 1}, source=a.uuid))
        deadline = time.monotonic() + 10.0
        while not got and time.monotonic() < deadline:
            time.sleep(0.02)
        assert got and got[0].type == "ping"
        assert M.FAULTS_INJECTED.labels("dcn.drop_tx").value \
            >= injected_before + 1
    finally:
        a.stop()
        b.stop()


def test_exec_crash_fails_one_batch_typed_lane_survives():
    """serve.exec.crash: the faulted batch's waiter gets the typed
    `internal` error; the NEXT request on the same lane succeeds."""
    from freedm_tpu.serve import ServeConfig, ServeError, Service

    svc = Service(ServeConfig(max_batch=2, buckets=(1, 2), cache_mb=0.0))
    try:
        # Warm the engine first so the crash hits a compiled path.
        svc.request("pf", {"case": "case14", "timeout_s": 300.0})
        FAULTS.configure("seed=2;serve.exec.crash:1:max=1")
        with pytest.raises(ServeError) as ei:
            svc.request("pf", {"case": "case14", "scale": 1.01,
                               "timeout_s": 60.0})
        assert ei.value.code == "internal"
        # Lane survived: the very next dispatch answers normally.
        resp = svc.request("pf", {"case": "case14", "scale": 1.02,
                                  "timeout_s": 60.0})
        assert resp.converged
    finally:
        FAULTS.reset()
        svc.stop(drain_s=0)


def test_cache_corruption_is_caught_by_residual_verify():
    """serve.cache.corrupt perturbs every delta-tier candidate BEFORE
    the float64 verify: no corrupted answer may be served — the tier
    falls through, the answers stay correct, and the delta hit counter
    stays frozen."""
    import numpy as np

    from freedm_tpu.serve import ServeConfig, Service

    svc = Service(ServeConfig(max_batch=2, buckets=(1, 2)))
    try:
        n = 14
        zeros = [0.0] * n
        base = {"case": "case14", "timeout_s": 300.0,
                "p_inj": zeros, "q_inj": zeros}
        first = svc.request("pf", base)  # populates the cache
        assert first.converged
        # A rank-1 perturbation of the cached base injections: delta-
        # tier traffic.  With corruption injected at rate 1, the verify
        # must reject every candidate.
        p = list(zeros)
        p[2] = -0.01
        FAULTS.configure("seed=3;serve.cache.corrupt:1:arg=0.05")
        delta_hits_before = M.SERVE_CACHE_HITS.labels("delta").value
        resp = svc.request("pf", {"case": "case14", "timeout_s": 300.0,
                                  "p_inj": p, "q_inj": [0.0] * n,
                                  "return_state": True})
        assert resp.converged
        assert resp.batch.tier in ("full",)  # fell through, never "delta"
        assert M.SERVE_CACHE_HITS.labels("delta").value == delta_hits_before
        assert resp.residual_pu < 1e-6
        # The served voltages are a REAL solution (not the corrupted
        # candidate): re-solving with the cache off agrees.
        FAULTS.reset()
        svc_off = Service(ServeConfig(max_batch=2, buckets=(1, 2),
                                      cache_mb=0.0))
        try:
            ref = svc_off.request("pf", {"case": "case14",
                                         "timeout_s": 300.0, "p_inj": p,
                                         "q_inj": [0.0] * n,
                                         "return_state": True})
            np.testing.assert_allclose(resp.v, ref.v, atol=1e-6)
        finally:
            svc_off.stop(drain_s=0)
    finally:
        FAULTS.reset()
        svc.stop(drain_s=0)


def test_qsts_worker_crash_requeues_from_checkpoint(tmp_path):
    """qsts.worker.crash at the first chunk boundary: the job manager
    requeues the job, the rerun resumes from the chunk checkpoint, and
    the final summary is a normal completion."""
    from freedm_tpu.scenarios.jobs import JobManager

    events_before = len(M.EVENTS)
    requeued_before = M.QSTS_REQUEUED.value
    FAULTS.configure("seed=4;qsts.worker.crash:1:max=1")
    jm = JobManager(workers=1, checkpoint_dir=str(tmp_path)).start()
    try:
        out = jm.submit({"case": "case14", "scenarios": 2, "steps": 12,
                         "chunk_steps": 4, "seed": 9,
                         "job_key": "crashprobe"})
        job_id = out["job_id"]
        deadline = time.monotonic() + 300.0
        j = {}
        while time.monotonic() < deadline:
            j = jm.get(job_id)
            if j["state"] in ("completed", "failed", "cancelled"):
                break
            time.sleep(0.2)
        assert j["state"] == "completed", j
        assert j["requeues"] == 1
        # The requeue's crash record must not survive a successful
        # completion — "completed" with an "error" key misreads as
        # failure to pollers.
        assert "error" not in j
        # The rerun RESUMED (chunk 1's checkpoint was on disk when the
        # crash fired after chunk 1 completed).
        assert j["summary"]["resumed_from_chunk"] >= 1
        assert M.QSTS_REQUEUED.value == requeued_before + 1
        tail = M.EVENTS.tail(len(M.EVENTS) - events_before)
        assert any(e.get("event") == "qsts.requeued" for e in tail)
    finally:
        FAULTS.reset()
        jm.stop()


def test_unkeyed_job_crash_fails_instead_of_silent_restart(tmp_path):
    from freedm_tpu.scenarios.jobs import JobManager

    FAULTS.configure("seed=4;qsts.worker.crash:1:max=1")
    jm = JobManager(workers=1, checkpoint_dir=str(tmp_path)).start()
    try:
        out = jm.submit({"case": "case14", "scenarios": 2, "steps": 12,
                         "chunk_steps": 4, "seed": 9})  # no job_key
        deadline = time.monotonic() + 300.0
        j = {}
        while time.monotonic() < deadline:
            j = jm.get(out["job_id"])
            if j["state"] in ("completed", "failed", "cancelled"):
                break
            time.sleep(0.2)
        assert j["state"] == "failed", j
        assert "qsts.worker.crash" in j["error"]
        assert j["requeues"] == 0
    finally:
        FAULTS.reset()
        jm.stop()
