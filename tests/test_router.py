"""Replica-router semantics (``freedm_tpu.serve.router``): hash-ring
affinity stability under join/leave, retry-respects-deadline, breaker
open/half-open/close transitions, drain completes in-flight, and the
kill-one-of-three failover answering byte-identically via a survivor.

The protocol tests (retry/breaker/drain) run against scripted STUB
replicas — plain HTTP servers with programmable behavior — so they pin
router semantics without paying solver compiles.  The failover
byte-identity test runs three REAL serve stacks.
"""

import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler

from freedm_tpu.core import metrics as M
from freedm_tpu.core.metrics import BackgroundHttpServer
from freedm_tpu.serve.router import (
    HashRing,
    Router,
    RouterConfig,
    RouterServer,
)

# ---------------------------------------------------------------------------
# stub replicas
# ---------------------------------------------------------------------------


class StubReplica:
    """A scripted replica: ``behavior`` keys steer every request.

    ``fail_500`` — answer that many requests with a typed internal 500;
    ``sleep_s`` — stall each POST; ``draining`` — reported on /healthz;
    ``refuse`` — close the listener entirely (connection refused).
    """

    def __init__(self, **behavior):
        self.behavior = dict(behavior)
        self.posts = 0
        self.budgets = []  # X-Deadline-Budget-S header per request
        stub = self

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _send(self, code, obj):
                data = (json.dumps(obj) + "\n").encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                self._send(200, {
                    "ok": True,
                    "draining": stub.behavior.get("draining", False),
                })

            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(n)
                stub.posts += 1
                stub.budgets.append(
                    self.headers.get("X-Deadline-Budget-S")
                )
                if stub.behavior.get("sleep_s"):
                    time.sleep(stub.behavior["sleep_s"])
                if stub.behavior.get("overloaded"):
                    self._send(429, {"error": {"type": "overloaded",
                                               "detail": "scripted"}})
                    return
                if stub.behavior.get("fail_500", 0) > 0:
                    stub.behavior["fail_500"] -= 1
                    self._send(500, {"error": {"type": "internal",
                                               "detail": "scripted"}})
                    return
                self._send(200, {"ok": True, "echo": json.loads(body)})

        self.server = BackgroundHttpServer(H, port=0).start()
        self.port = self.server.port
        self.id = f"127.0.0.1:{self.port}"

    def stop(self):
        self.server.stop()


def _post(port, case, timeout_s=5.0, client_timeout=30.0):
    body = json.dumps({"case": case, "timeout_s": timeout_s}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/pf", data=body,
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=client_timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        payload = json.loads(e.read())
        headers = dict(e.headers)
        e.close()
        return e.code, payload, headers


# ---------------------------------------------------------------------------
# hash-ring affinity
# ---------------------------------------------------------------------------


def test_ring_affinity_stable_under_leave_and_join():
    ring = HashRing(vnodes=64)
    members = ["10.0.0.1:1", "10.0.0.2:2", "10.0.0.3:3"]
    for m in members:
        ring.add(m)
    keys = [f"case{i}" for i in range(300)]
    owners = {k: ring.owner(k) for k in keys}
    assert set(owners.values()) == set(members)  # every replica owns range

    # LEAVE: only the departed member's keys move.
    ring.remove(members[1])
    for k in keys:
        if owners[k] != members[1]:
            assert ring.owner(k) == owners[k], k
        else:
            assert ring.owner(k) != members[1]
    # JOIN back: the original mapping returns exactly.
    ring.add(members[1])
    assert {k: ring.owner(k) for k in keys} == owners

    # The preference list starts at the owner and covers every member.
    pref = ring.preference(keys[0])
    assert pref[0] == owners[keys[0]]
    assert sorted(pref) == sorted(members)


def test_router_routes_same_case_to_same_replica():
    a, b = StubReplica(), StubReplica()
    router = Router([a.id, b.id], RouterConfig())
    srv = RouterServer(router, port=0)
    srv._server.start()  # no prober: deterministic stub accounting
    try:
        served = set()
        for _ in range(4):
            code, _, headers = _post(srv.port, "caseAffinity")
            assert code == 200
            served.add(headers.get("X-Served-By"))
        assert len(served) == 1  # affinity held across repeats
        assert served.pop() == router.ring.owner("caseAffinity")
    finally:
        srv._server.stop()
        a.stop()
        b.stop()


# ---------------------------------------------------------------------------
# retry budget
# ---------------------------------------------------------------------------


def test_retry_respects_deadline_budget():
    """Dead replicas: the router retries with backoff but NEVER past
    the request's own deadline — a typed answer arrives promptly after
    the budget, not after some unrelated retry cap."""
    a = StubReplica()
    a.stop()  # connection refused from here on
    router = Router([a.id], RouterConfig(
        breaker_failures=1000,  # keep the breaker out of this test
        retry_base_s=0.01,
    ))
    srv = RouterServer(router, port=0)
    srv._server.start()
    try:
        t0 = time.monotonic()
        code, payload, headers = _post(srv.port, "case14", timeout_s=0.6)
        elapsed = time.monotonic() - t0
        assert payload["error"]["type"] == "deadline_exceeded"
        assert code == 504
        # Bounded promptly by the budget (generous slack for CI).
        assert 0.5 <= elapsed < 3.0, elapsed
        assert M.ROUTER_RETRIES.value >= 1
    finally:
        srv._server.stop()


def test_deadline_budget_header_propagates_and_shrinks():
    a = StubReplica(fail_500=1)
    router = Router([a.id], RouterConfig(
        breaker_failures=1000, retry_base_s=0.05, retry_cap_s=0.05,
    ))
    try:
        reply = router.route(
            "/v1/pf",
            json.dumps({"case": "x", "timeout_s": 4.0}).encode(),
        )
        assert reply.status == 200
        budgets = [float(b) for b in a.budgets]
        assert len(budgets) == 2  # the 500, then the retry
        assert budgets[0] <= 4.0
        assert budgets[1] < budgets[0]  # the budget SHRANK across retry
    finally:
        a.stop()


def test_per_replica_429_fails_over_immediately():
    """An overloaded owner must not be hammered until the deadline:
    the request fails over to the next ring replica at once, and a
    fully-shedding fleet propagates the typed 429 promptly."""
    a = StubReplica(overloaded=True)
    b = StubReplica()
    router = Router([a.id, b.id], RouterConfig(retry_base_s=0.01))
    case = next(f"case{i}" for i in range(200)
                if router.ring.owner(f"case{i}") == a.id)
    try:
        t0 = time.monotonic()
        reply = router.route(
            "/v1/pf", json.dumps({"case": case, "timeout_s": 20}).encode()
        )
        assert reply.status == 200 and reply.served_by == b.id
        assert time.monotonic() - t0 < 2.0  # no backoff burn
        assert a.posts == 1  # asked once, then skipped for the request

        # The WHOLE fleet shedding: typed 429 back to the client,
        # promptly, with Retry-After — never a 504 deadline burn.
        b.behavior["overloaded"] = True
        t0 = time.monotonic()
        reply = router.route(
            "/v1/pf", json.dumps({"case": case, "timeout_s": 20}).encode()
        )
        assert reply.status == 429
        assert json.loads(reply.body)["error"]["type"] == "overloaded"
        assert reply.retry_after is not None
        assert time.monotonic() - t0 < 2.0
    finally:
        a.stop()
        b.stop()


def test_typed_client_errors_pass_through_unretried():
    a = StubReplica()
    router = Router([a.id], RouterConfig())
    try:
        # Unknown workload: router-side typed 400, no proxy at all.
        reply = router.route("/v1/nope", json.dumps({"case": "x"}).encode())
        assert reply.status == 400
        assert json.loads(reply.body)["error"]["type"] == "invalid_request"
        assert a.posts == 0
        # Missing case: also router-side.
        reply = router.route("/v1/pf", b"{}")
        assert reply.status == 400
    finally:
        a.stop()


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------


def test_breaker_open_half_open_close_transitions():
    a = StubReplica()
    b = StubReplica()
    router = Router([a.id, b.id], RouterConfig(
        breaker_failures=2, breaker_cooldown_s=0.3, retry_base_s=0.005,
    ))
    # Find a case owned by A so its failures are what we script.
    case = next(f"case{i}" for i in range(200)
                if router.ring.owner(f"case{i}") == a.id)
    a.stop()  # A is dead: connection refused
    try:
        # Two requests -> two A-failures -> breaker OPEN (answers still
        # arrive via failover to B).
        for _ in range(2):
            reply = router.route(
                "/v1/pf",
                json.dumps({"case": case, "timeout_s": 5}).encode(),
            )
            assert reply.status == 200
            assert reply.served_by == b.id
        assert router.states()[a.id]["breaker"] == "open"
        assert M.ROUTER_FAILOVERS.value >= 2

        # While OPEN (inside cooldown) A is never tried again.
        posts_before = b.posts
        reply = router.route(
            "/v1/pf", json.dumps({"case": case, "timeout_s": 5}).encode()
        )
        assert reply.status == 200 and reply.served_by == b.id
        assert b.posts == posts_before + 1

        # Revive A on the SAME port, wait out the cooldown: the next
        # request is the half-open trial, succeeds, and CLOSES it.
        a2 = _revive(a.port)
        try:
            time.sleep(0.35)
            reply = router.route(
                "/v1/pf",
                json.dumps({"case": case, "timeout_s": 5}).encode(),
            )
            assert reply.status == 200 and reply.served_by == a.id
            assert router.states()[a.id]["breaker"] == "closed"
        finally:
            a2.stop()
    finally:
        b.stop()


def _revive(port):
    """A fresh stub bound to a specific (just-freed) port."""
    class H(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def _send(self, code, obj):
            data = (json.dumps(obj) + "\n").encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            self._send(200, {"ok": True, "draining": False})

        def do_POST(self):
            n = int(self.headers.get("Content-Length") or 0)
            self.rfile.read(n)
            self._send(200, {"ok": True, "revived": True})

    return BackgroundHttpServer(H, port=port).start()


def test_all_replicas_open_sheds_typed_503_with_retry_after():
    a = StubReplica()
    a.stop()
    router = Router([a.id], RouterConfig(
        breaker_failures=1, breaker_cooldown_s=60.0, retry_base_s=0.005,
    ))
    shed_before = M.ROUTER_SHED.value
    # First request opens the breaker (and dies on the deadline);
    # second finds NO admittable replica -> typed unavailable shed.
    router.route("/v1/pf", json.dumps({"case": "x", "timeout_s": 0.2}).encode())
    reply = router.route(
        "/v1/pf", json.dumps({"case": "x", "timeout_s": 5}).encode()
    )
    assert reply.status == 503
    assert json.loads(reply.body)["error"]["type"] == "unavailable"
    assert reply.retry_after is not None and int(reply.retry_after) >= 1
    assert M.ROUTER_SHED.value > shed_before


# ---------------------------------------------------------------------------
# drain
# ---------------------------------------------------------------------------


def test_drained_replica_stops_receiving_new_work_inflight_completes():
    a = StubReplica(sleep_s=0.4)
    b = StubReplica()
    router = Router([a.id, b.id], RouterConfig())
    case = next(f"case{i}" for i in range(200)
                if router.ring.owner(f"case{i}") == a.id)
    try:
        results = {}

        def inflight():
            results["reply"] = router.route(
                "/v1/pf",
                json.dumps({"case": case, "timeout_s": 10}).encode(),
            )

        t = threading.Thread(target=inflight)
        t.start()
        time.sleep(0.1)  # the request is now sleeping inside A
        router.drain(a.id)
        # An active probe must NOT undo the administrative drain (A's
        # own /healthz still says draining:false — the router-side
        # decision outranks it).
        router.probe_once()
        assert router.states()[a.id]["draining"] is True
        # New work for A's range fails over to B immediately...
        reply = router.route(
            "/v1/pf", json.dumps({"case": case, "timeout_s": 5}).encode()
        )
        assert reply.status == 200 and reply.served_by == b.id
        # ...while the in-flight request COMPLETES on A (drain never
        # cuts accepted work).
        t.join(timeout=5)
        assert results["reply"].status == 200
        assert results["reply"].served_by == a.id
    finally:
        a.stop()
        b.stop()


def test_prober_marks_draining_replica_from_healthz():
    a = StubReplica(draining=True)
    b = StubReplica()
    router = Router([a.id, b.id], RouterConfig())
    try:
        router.probe_once()
        assert router.states()[a.id]["draining"] is True
        case = next(f"case{i}" for i in range(200)
                    if router.ring.owner(f"case{i}") == a.id)
        reply = router.route(
            "/v1/pf", json.dumps({"case": case, "timeout_s": 5}).encode()
        )
        assert reply.status == 200 and reply.served_by == b.id
    finally:
        a.stop()
        b.stop()


# ---------------------------------------------------------------------------
# kill-one-of-three: byte-identical answers via the survivor
# ---------------------------------------------------------------------------


def test_kill_one_of_three_survivor_answers_byte_identical():
    """Three REAL serve stacks behind the router: kill the replica that
    owns case14 mid-session; the re-routed request must return the
    byte-identical solver answer (the receipt aside) from a survivor."""
    from freedm_tpu.serve import ServeConfig, ServeServer, Service

    stacks = []
    try:
        for _ in range(3):
            svc = Service(ServeConfig(max_batch=4, buckets=(1, 2, 4)))
            srv = ServeServer(svc, port=0).start()
            stacks.append((svc, srv))
        router = Router(
            [f"127.0.0.1:{srv.port}" for _, srv in stacks],
            RouterConfig(breaker_failures=1, retry_base_s=0.01),
        )
        rs = RouterServer(router, port=0)
        rs._server.start()
        try:
            body = {"case": "case14", "return_state": True,
                    "timeout_s": 300.0}
            req = urllib.request.Request(
                f"http://127.0.0.1:{rs.port}/v1/pf",
                data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=310) as r:
                first = json.loads(r.read())
                owner = r.headers.get("X-Served-By")
            assert owner == router.ring.owner("case14")
            # Kill the owner (server AND service): abrupt, no drain.
            victim = next(
                (svc, srv) for svc, srv in stacks
                if f"127.0.0.1:{srv.port}" == owner
            )
            victim[1].stop()
            victim[0].stop(drain_s=0)
            with urllib.request.urlopen(req, timeout=310) as r:
                second = json.loads(r.read())
                survivor = r.headers.get("X-Served-By")
            assert survivor != owner
            # Byte-identical solver answer: same case, same flat start,
            # same compiled program — only the batching receipt may
            # differ between replicas.
            first.pop("batch")
            second.pop("batch")
            assert first == second
        finally:
            rs._server.stop()
    finally:
        for svc, srv in stacks:
            try:
                srv.stop()
                svc.stop(drain_s=0)
            except Exception:
                pass
