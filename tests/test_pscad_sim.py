"""PSCAD simulation-protocol tests (VERDICT r3 missing #6).

The plantserver now also speaks the line-oriented simulation protocol
of ``pscad-interface-master/src/CSimulationAdapter.cpp``: a PSCAD
co-simulation pushes measured states (5-byte RST/SET header + doubles)
and polls the DGI-commanded values (GET), alongside the RTDS byte
protocol the DGI side uses.
"""

import socket

import numpy as np
import pytest

from freedm_tpu.core.config import NULL_COMMAND
from freedm_tpu.devices.adapters.plant import PlantAdapter
from freedm_tpu.devices.adapters.rtds import WIRE_DTYPE, read_exactly
from freedm_tpu.grid import cases
from freedm_tpu.sim.plantserver import SIM_DTYPE, SIM_HEADER_SIZE, PlantServer


def header(kind: str) -> bytes:
    return kind.encode().ljust(SIM_HEADER_SIZE, b"\x00")


@pytest.fixture
def rig():
    plant = PlantAdapter(
        cases.vvc_9bus(),
        {"LOAD_A": ("Load", 0), "DRER_A": ("Drer", 1), "SST1": ("Sst", 2)},
    )
    plant.reveal_devices()
    server = PlantServer(plant, period_s=0.01)
    sim_addr = server.add_port(
        states=[("LOAD_A", "drain"), ("DRER_A", "generation")],
        commands=[("SST1", "gateway")],
        protocol="pscad",
    )
    rtds_addr = server.add_port(
        states=[("LOAD_A", "drain"), ("SST1", "gateway")],
        commands=[("SST1", "gateway")],
    )
    server.start()
    yield plant, server, sim_addr, rtds_addr
    server.stop()


def test_set_pushes_states_into_the_plant(rig):
    plant, server, sim_addr, _ = rig
    with socket.create_connection(sim_addr, timeout=5) as s:
        s.sendall(header("SET") + np.asarray([25.0, 40.0], SIM_DTYPE).tobytes())
        # Second message on the same connection (the protocol loops).
        s.sendall(header("SET") + np.asarray([26.0, 41.0], SIM_DTYPE).tobytes())
        s.sendall(header("GET"))
        read_exactly(s, SIM_DTYPE.itemsize)  # sync: both SETs processed
    assert plant.get_state("LOAD_A", "drain") == 26.0
    assert plant.get_state("DRER_A", "generation") == 41.0


def test_get_reads_back_dgi_commands(rig):
    plant, server, sim_addr, _ = rig
    plant.set_command("SST1", "gateway", 7.5)  # what the DGI commanded
    with socket.create_connection(sim_addr, timeout=5) as s:
        s.sendall(header("GET"))
        raw = read_exactly(s, 1 * SIM_DTYPE.itemsize)
    assert np.frombuffer(raw, SIM_DTYPE)[0] == 7.5


def test_rst_seeds_commands_from_states():
    """RST's COMMAND_TABLE ← STATE_TABLE copy: the seeded command
    survives a later SET that moves the state — GET keeps returning the
    seed, distinguishing RST from plain SET."""
    plant = PlantAdapter(cases.vvc_9bus(), {"DESD1": ("Desd", 0)})
    plant.reveal_devices()
    server = PlantServer(plant, period_s=0.01)
    addr = server.add_port(
        states=[("DESD1", "storage")],
        commands=[("DESD1", "storage")],
        protocol="pscad",
    )
    server.start()
    try:
        with socket.create_connection(addr, timeout=5) as s:
            s.sendall(header("RST") + np.asarray([5.0], SIM_DTYPE).tobytes())
            s.sendall(header("SET") + np.asarray([9.0], SIM_DTYPE).tobytes())
            s.sendall(header("GET"))
            raw = read_exactly(s, SIM_DTYPE.itemsize)
        # State followed the SET; the command kept the RST seed.
        assert plant.get_state("DESD1", "storage") == 9.0
        assert np.frombuffer(raw, SIM_DTYPE)[0] == 5.0
    finally:
        server.stop()


def test_unknown_device_binding_warns_not_kills(rig):
    """A typo'd binding must not kill the serving thread: the rest of
    the message applies and the connection keeps serving."""
    plant, server, sim_addr, _ = rig
    server._ports[0].states.insert(0, ("TYPO", "drain"))
    with socket.create_connection(sim_addr, timeout=5) as s:
        s.sendall(
            header("SET") + np.asarray([1.0, 27.0, 0.0], SIM_DTYPE).tobytes()
        )
        s.sendall(header("GET"))
        read_exactly(s, SIM_DTYPE.itemsize)  # connection still alive
    assert plant.get_state("LOAD_A", "drain") == 27.0


def test_unknown_header_closes_connection_but_server_survives(rig):
    """An unknown verb's payload length is unknowable: the connection
    closes (no stream desync) and a reconnect is served normally."""
    plant, server, sim_addr, _ = rig
    with socket.create_connection(sim_addr, timeout=5) as s:
        s.sendall(header("XYZ"))
        assert s.recv(1) == b""  # server closed the desynced stream
    with socket.create_connection(sim_addr, timeout=5) as s:
        s.sendall(header("GET"))
        raw = read_exactly(s, SIM_DTYPE.itemsize)
    assert len(raw) == SIM_DTYPE.itemsize


def test_pscad_and_rtds_ports_cohabit(rig):
    """A PSCAD-side load change is visible through the DGI's RTDS port
    on the same plant — the two protocols share one physics."""
    plant, server, sim_addr, rtds_addr = rig
    with socket.create_connection(sim_addr, timeout=5) as sim:
        sim.sendall(header("SET") + np.asarray([33.0, 0.0], SIM_DTYPE).tobytes())
        sim.sendall(header("GET"))
        read_exactly(sim, SIM_DTYPE.itemsize)
    with socket.create_connection(rtds_addr, timeout=5) as dgi:
        cmds = np.full(1, NULL_COMMAND, WIRE_DTYPE)
        dgi.sendall(cmds.tobytes())
        raw = read_exactly(dgi, 2 * 4)
    states = np.frombuffer(raw, WIRE_DTYPE)
    assert states[0] == pytest.approx(33.0)


def test_load_rig_builds_pscad_port(tmp_path):
    xml = """<rig case="vvc_9bus" period="0.02">
      <device name="LOAD_A" type="Load" node="0" value="10"/>
      <adapter port="0" protocol="pscad">
        <state device="LOAD_A" signal="drain" index="0"/>
      </adapter>
    </rig>"""
    from freedm_tpu.sim.plantserver import load_rig

    server = load_rig(xml)
    assert server._ports[0].protocol == "pscad"
    server.start()
    try:
        addr = server.port_address(0)
        with socket.create_connection(addr, timeout=5) as s:
            s.sendall(header("SET") + np.asarray([12.0], SIM_DTYPE).tobytes())
        import time

        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if server.plant.get_state("LOAD_A", "drain") == 12.0:
                break
            time.sleep(0.01)
        assert server.plant.get_state("LOAD_A", "drain") == 12.0
    finally:
        server.stop()
