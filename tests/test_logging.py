"""CLogger-equivalent tests: 9 levels, global default, per-logger override."""

from freedm_tpu.core import logging as dlog


def test_levels_table():
    assert dlog.LEVELS == (
        "FATAL",
        "ALERT",
        "ERROR",
        "WARN",
        "STATUS",
        "NOTICE",
        "INFO",
        "DEBUG",
        "TRACE",
    )


def test_global_level_applies_to_later_loggers():
    dlog.set_global_level(8)
    lg = dlog.get_logger("made-after-global-set")
    assert lg.level == 8
    dlog.set_global_level(5)
    assert lg.level == 5  # retroactive too


def test_configure_from_file(tmp_path):
    p = tmp_path / "logger.cfg"
    p.write_text("default = 4\nCBroker = 8\n")
    dlog.configure_from_file(p)
    assert dlog.get_logger("CBroker").level == 8
    assert dlog.get_logger("other").level == 4
    assert "CBroker" in dlog.list_loggers()
    dlog.set_global_level(5)
