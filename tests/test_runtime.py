"""Runtime tests: broker scheduling, dispatch, and the 3-node
end-to-end demo (BASELINE.md config #1: the reference's 3-node LB+SC
deployment with fake devices, here one fleet program over a shared
JAX plant).
"""

import time

import jax.numpy as jnp
import numpy as np
import pytest

from freedm_tpu.devices.adapters.plant import NOMINAL_OMEGA, PlantAdapter
from freedm_tpu.devices.manager import DeviceManager
from freedm_tpu.grid import cases
from freedm_tpu.modules import lb, sc
from freedm_tpu.runtime import (
    Broker,
    DgiModule,
    Fleet,
    ModuleMessage,
    NodeHandle,
    PeerList,
    build_broker,
)


class Recorder(DgiModule):
    def __init__(self, name):
        self.name = name
        self.phases = []
        self.messages = []

    def run_phase(self, ctx):
        self.phases.append(ctx.round_index)

    def handle_message(self, msg, ctx=None):
        self.messages.append(msg)


def test_broker_phase_order_and_rounds():
    b = Broker()
    m1, m2 = Recorder("a"), Recorder("b")
    b.register_module(m1, 10)
    b.register_module(m2, 20)
    assert b.round_length_ms == 30
    done = b.run(n_rounds=3)
    assert done == 3
    assert m1.phases == m2.phases == [0, 1, 2]


def test_broker_message_queueing_and_broadcast():
    b = Broker()
    m1, m2 = Recorder("a"), Recorder("b")
    b.register_module(m1, 10)
    b.register_module(m2, 10)
    # Messages dispatched before a round run in the recipient's phase.
    assert b.deliver(ModuleMessage("a", "ping")) == 1
    assert b.deliver(ModuleMessage("all", "bcast")) == 2
    b.run(n_rounds=1)
    assert [m.type for m in m1.messages] == ["ping", "bcast"]
    assert [m.type for m in m2.messages] == ["bcast"]
    # Expired messages are dropped at dispatch (real-time semantics).
    stale = ModuleMessage("a", "late").expiring(-1.0)
    assert b.deliver(stale) == 0
    assert b.dispatcher.dropped_expired == 1


def test_broker_timers_fire_in_module_phase():
    b = Broker()
    m = Recorder("a")
    fired = []
    b.register_module(m, 10)
    t = b.allocate_timer("a")
    b.schedule_timer(t, 0.0, lambda: fired.append(b.round_index))
    b.run(n_rounds=2)
    assert fired == [0]


def test_peer_loopback_shortcircuit():
    got = []
    pl = PeerList("me:1", loopback=got.append)
    pl.get("me:1").send(ModuleMessage("lb", "hello"))
    assert got and got[0].type == "hello"
    with pytest.raises(ValueError):
        pl.add("other:2", None)  # remote peer requires a transport


# ---------------------------------------------------------------------------
# 3-node end-to-end demo
# ---------------------------------------------------------------------------


@pytest.fixture
def three_node_fleet():
    feeder = cases.vvc_9bus()
    placements = {
        # node A (supply): surplus 20 kW
        "SST1": ("Sst", 2),
        "DRER_A": ("Drer", 1),
        "LOAD_A": ("Load", 0),
        # node B (demand): deficit 20 kW
        "SST2": ("Sst", 4),
        "LOAD_B": ("Load", 5),
        "DRER_B": ("Drer", 6),
        # node C (balanced)
        "SST3": ("Sst", 7),
        "LOAD_C": ("Load", 3),
        "DRER_C": ("Drer", 3),
        "OMEGA": ("Omega", 0),
    }
    plant = PlantAdapter(feeder, placements, droop=0.05)
    managers = []
    owned = [
        ["SST1", "DRER_A", "LOAD_A", "OMEGA"],
        ["SST2", "LOAD_B", "DRER_B"],
        ["SST3", "LOAD_C", "DRER_C"],
    ]
    for names in owned:
        m = DeviceManager(capacity=8)
        for n in names:
            m.add_device(n, placements[n][0], plant)
        managers.append(m)
    plant.reveal_devices()
    plant.set_generation("DRER_A", 30.0)
    plant.set_load("LOAD_A", 10.0)
    plant.set_load("LOAD_B", 30.0)
    plant.set_generation("DRER_B", 10.0)
    plant.set_load("LOAD_C", 20.0)
    plant.set_generation("DRER_C", 20.0)
    plant.start()

    fleet = Fleet(
        [NodeHandle(f"host{i}:5187{i}", m) for i, m in enumerate(managers)],
        migration_step=1.0,
    )
    fleet.plants.append(plant)
    return fleet, plant


def test_three_node_demo_converges(three_node_fleet):
    fleet, plant = three_node_fleet
    broker = build_broker(fleet)
    broker.run(n_rounds=30)

    r = fleet.read_devices()
    gw = np.asarray(r["gateway"])
    # Supply exported its surplus, demand imported its deficit
    # (reference 3-node LB outcome after its 3000 ms rounds).
    np.testing.assert_allclose(gw, [20.0, -20.0, 0.0], atol=1.01)
    out = broker.shared["lb_round"]
    assert int(out.n_migrations) == 0  # converged: no more drafts
    # Everyone inside the ±step band.
    assert np.all(np.asarray(out.state) == lb.NORMAL)
    # The balanced system's frequency is near nominal.
    assert plant.omega == pytest.approx(NOMINAL_OMEGA, rel=0.02)
    # SC's collected view agrees: group gateway total ~ 0 (honest run).
    cs = broker.shared["collected"]
    assert float(jnp.max(jnp.abs(sc.invariant_total(cs)))) < 1.01


def test_node_failure_reforms_groups(three_node_fleet):
    fleet, plant = three_node_fleet
    broker = build_broker(fleet)
    broker.run(n_rounds=5)
    assert int(broker.shared["group"].n_groups) == 1

    # Kill the supply node: the AYT-timeout -> Recovery path.
    fleet.set_alive(0, False)
    broker.run(n_rounds=3)
    g = broker.shared["group"]
    assert int(g.n_groups) == 1  # B and C regroup
    assert int(g.coordinator[0]) == -1
    assert np.asarray(g.group_mask)[1, 0] == 0
    # Demand can no longer be served (no supply in the group): the
    # incomplete-coverage outcome, not an error.
    out = broker.shared["lb_round"]
    assert int(out.state[1]) == lb.DEMAND
    assert int(out.n_migrations) == 0

    # Node A returns: merge back into one 3-node group (re-election).
    fleet.set_alive(0, True)
    broker.run(n_rounds=3)
    g2 = broker.shared["group"]
    assert int(g2.n_groups) == 1
    assert int(g2.group_size[0]) == 3


def test_malicious_node_detected_by_ledger(three_node_fleet):
    fleet, plant = three_node_fleet
    fleet.malicious = jnp.asarray([0.0, 1.0, 0.0])  # demand node B cheats
    broker = build_broker(fleet)
    broker.run(n_rounds=3)
    cs = broker.shared["collected"]
    out = broker.shared["lb_round"]
    # The cut's conserved total differs from the raw gateway sum by the
    # unapplied quanta — the discrepancy SC exists to surface.
    assert float(jnp.sum(out.intransit)) < 0.0


def test_allocate_timer_distinct_handles():
    broker = Broker()
    rec = Recorder("m")
    broker.register_module(rec, 10)
    t1 = broker.allocate_timer("m")
    t2 = broker.allocate_timer("m")
    assert t1 != t2
    fired = []
    broker.schedule_timer(t1, 0.0, lambda: fired.append("a"))
    broker.schedule_timer(t2, 0.0, lambda: fired.append("b"))
    assert broker.cancel_timers(t2) == 1
    time.sleep(0.01)
    broker.run(n_rounds=1)
    assert fired == ["a"]


def test_timer_handle_survives_firing_for_rearm():
    # AllocateTimer allocate-once/reschedule pattern: a timer callback
    # re-arming its own handle must not raise (round-2 advisor finding).
    broker = Broker()
    rec = Recorder("m")
    broker.register_module(rec, 10)
    t = broker.allocate_timer("m")
    fired = []

    def cb():
        fired.append(len(fired))
        if len(fired) < 3:
            broker.schedule_timer(t, 0.0, cb)

    broker.schedule_timer(t, 0.0, cb)
    broker.run(n_rounds=5)
    assert fired == [0, 1, 2]
    assert broker.cancel_timers(t) == 0  # released only here


def test_fleet_fid_duplicate_name_prefers_live_reading(three_node_fleet):
    # Same breaker name exposed by two nodes: a dead node's forced-open 0
    # must not mask the live node's actual reading, and vice versa the
    # conservative open state must win among live conflicts (min).
    fleet, plant = three_node_fleet
    from freedm_tpu.devices.adapters.fake import FakeAdapter

    fake = FakeAdapter()
    fleet.nodes[0].manager.add_device("FID_X", "Fid", fake)
    fake2 = FakeAdapter()
    fleet.nodes[2].manager.add_device("FID_X", "Fid", fake2)
    fake.reveal_devices()
    fake2.reveal_devices()
    fake.set_state("FID_X", "state", 1.0)
    fake2.set_state("FID_X", "state", 1.0)
    fleet.fid_names = ("FID_X",)
    # Node 2 dies: its copy reads forced 0, but node 0 is live with 1.0.
    fleet.set_alive(2, False)
    np.testing.assert_allclose(np.asarray(fleet.fid_states()), [1.0])
    # Both live but disagreeing: fail-open (min).
    fleet.set_alive(2, True)
    fake2.set_state("FID_X", "state", 0.0)
    np.testing.assert_allclose(np.asarray(fleet.fid_states()), [0.0])


def test_fleet_fid_states_topology_order(three_node_fleet):
    fleet, plant = three_node_fleet
    # Give nodes FID devices named like topology fid edges, registered in
    # an order that disagrees with topology order.
    from freedm_tpu.devices.adapters.fake import FakeAdapter

    fake = FakeAdapter()
    fleet.nodes[2].manager.add_device("FID_Z", "Fid", fake)
    fleet.nodes[0].manager.add_device("FID_A", "Fid", fake)
    fake.reveal_devices()
    fake.set_state("FID_Z", "state", 0.0)
    fake.set_state("FID_A", "state", 1.0)
    fleet.fid_names = ("FID_A", "FID_Z", "FID_MISSING")
    states = np.asarray(fleet.fid_states())
    # Topology order, with the unbacked FID defaulting to 0/open.
    np.testing.assert_allclose(states, [1.0, 0.0, 0.0])
    # Without fid_names, >1 FID is ambiguous and must raise.
    fleet.fid_names = None
    with pytest.raises(ValueError, match="fid_names"):
        fleet.fid_states()


# ---------------------------------------------------------------------------
# SC→LB synchronize + DeviceTensor ingress (VERDICT r3 item 2)
# ---------------------------------------------------------------------------


def test_lb_prediction_drifts_without_sc_and_collected_resets_it(three_node_fleet):
    """A malicious demand node accepts migrations it never actuates, so
    the predicted gateway drifts from the device cut; the next collected
    state resynchronizes the prediction (HandleCollectedState →
    Synchronize, lb/LoadBalance.cpp:1160-1231)."""
    from freedm_tpu.runtime.fleet import EgressModule, GmModule, LbModule

    fleet, plant = three_node_fleet
    fleet.malicious = jnp.asarray([0.0, 1.0, 0.0])  # demand node B cheats
    # A broker WITHOUT the SC phase: nothing resynchronizes LB.
    broker = Broker()
    lb_mod = LbModule(fleet)
    broker.register_module(GmModule(fleet), 0)
    broker.register_module(lb_mod, 0)
    broker.register_module(EgressModule(fleet), 0)
    broker.run(n_rounds=4)
    actual = np.asarray(fleet.read_devices()["gateway"])
    drift = np.abs(lb_mod.predicted - actual)
    # B's accepted-but-dropped steps accumulated in the prediction only.
    assert drift.max() > 1.5, (lb_mod.predicted, actual)
    assert lb_mod.syncs == 0
    # Deliver a collected cut the way the SC phase does: the prediction
    # resets to the actual readings and K to the conserved group total.
    r = fleet.read_devices()
    group = broker.shared["group"]
    cs = sc.collect(
        group.group_mask, r["gateway"], r["generation"], r["storage"],
        r["drain"], r["fid_min"], broker.shared["lb_intransit"],
    )
    lb_mod.synchronize(cs, r)
    np.testing.assert_allclose(lb_mod.predicted, actual)
    assert lb_mod.syncs == 1
    np.testing.assert_allclose(
        lb_mod.power_differential, np.asarray(sc.invariant_total(cs))
    )


def test_full_stack_synchronizes_every_round(three_node_fleet):
    """With SC in the loop (standard stack) the prediction resets every
    round — the SC→LB feedback loop is load-bearing."""
    fleet, plant = three_node_fleet
    fleet.malicious = jnp.asarray([0.0, 1.0, 0.0])
    broker = build_broker(fleet)
    broker.run(n_rounds=6)
    lb_mod = broker._by_name["lb"].module
    assert lb_mod.syncs >= 5  # one per round after the first cut
    assert lb_mod.normal is not None


def test_fleet_reads_and_writes_go_through_device_tensor():
    """Fleet ingress snapshots each node into a DeviceTensor and reduces
    on device; egress writes commands into the tensor and replays them
    through manager.apply_commands."""
    from freedm_tpu.devices import tensor as dtt
    from freedm_tpu.devices.adapters.fake import FakeAdapter

    fake = FakeAdapter(
        {
            ("SST", "gateway"): 3.0,
            ("DRER", "generation"): 30.0,
            ("LOAD", "drain"): 10.0,
        }
    )
    m = DeviceManager(capacity=4)
    for name, tname in [("SST", "Sst"), ("DRER", "Drer"), ("LOAD", "Load")]:
        m.add_device(name, tname, fake)
    fake.reveal_devices()
    fleet = Fleet([NodeHandle("n0:50870", m)])
    r = fleet.read_devices()
    assert float(r["netgen"][0]) == pytest.approx(20.0)
    assert float(r["gateway"][0]) == pytest.approx(3.0)
    # The ingress kept the per-node DeviceTensor, and its masked
    # reduction agrees with the scalar it produced.
    snap = fleet._snapshots[0]
    assert isinstance(snap, dtt.DeviceTensor)
    lay = m.layout
    assert float(
        dtt.net_value(snap, lay.type_ids["Sst"], lay.signal_index("gateway"))
    ) == pytest.approx(3.0)
    # Egress: the command lands on the adapter via apply_commands.
    fleet.write_gateways(np.asarray([7.5]))
    assert fake.get_state("SST", "gateway") == 7.5
