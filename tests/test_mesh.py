"""Mesh scale-out equivalence (ISSUE 6): every batched hot path run
sharded over a device mesh must be byte-identical to its unsharded
form, and QSTS chunk checkpoints must be placement-free (kill on one
device count, resume on another, bit-for-bit).

Adaptive to the host's virtual device count: conftest forces 8 CPU
devices by default, and CI re-runs this file under
``XLA_FLAGS=--xla_force_host_platform_device_count=4`` to prove the
count is not baked in anywhere.

The byte-identity tests run at the DEPLOYMENT dtype (f32 — the TPU
default; ``enable_x64(False)`` inside the harness's x64 config) on a
mesh of at most 4 devices.  What is bit-stable at any lane split: the
direct (LU) Newton solution path (v, theta, iteration counts) and the
ladder sweeps — their per-lane kernels are batched custom calls that
process each lane independently.  What is NOT: anything computed
through a vmap-collapsed GEMM/matvec, because the CPU backend's Eigen
GEMM re-tiles as the per-device row count changes — so the DERIVED
diagnostics (realized P/Q, residuals) and the Krylov path's iterates
(matvec inner loop) can move by ~eps; those are pinned to
dtype-epsilon closeness instead, and the x64 cousins to 1e-12.
The QSTS summary byte-identity tests are the acceptance contract and
hold at these shapes (GEMM tiling is deterministic per shape, so this
is stable, not flaky).
"""


import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental import enable_x64

from freedm_tpu.parallel.mesh import make_mesh
from freedm_tpu.scenarios.engine import (
    StudySpec,
    placement_free_spec,
    run_study,
    strip_timing,
)

D = jax.local_device_count()
#: The mesh size the sharded halves of the tests run at: the largest
#: power of two dividing the device count, capped at 4 (see module
#: docstring for why the cap).
D2 = max(d for d in (1, 2, 4) if d <= D and D % d == 0)

needs_mesh = pytest.mark.skipif(D2 < 2, reason="single-device host")


@pytest.fixture(scope="module")
def lane_mesh():
    return make_mesh(D2, axes=("batch",))


# ---------------------------------------------------------------------------
# solver wrappers: mesh-batched == vmap, byte for byte
# ---------------------------------------------------------------------------


@needs_mesh
def test_newton_mesh_batched_matches_vmap(lane_mesh):
    from freedm_tpu.grid.cases import synthetic_mesh
    from freedm_tpu.pf.newton import make_newton_solver

    sys_ = synthetic_mesh(60, seed=4, load_mw=2.0, chord_frac=1.0)
    lanes = 2 * D2
    rng = np.random.default_rng(0)
    scale = rng.uniform(0.9, 1.1, (lanes, 1))
    with enable_x64(False):
        solve, _ = make_newton_solver(sys_, max_iter=8)
        solve_m, solve_fixed_m = make_newton_solver(
            sys_, max_iter=8, mesh=lane_mesh
        )
        p = jnp.asarray(scale * np.asarray(sys_.p_inj)[None, :],
                        jnp.float32)
        q = jnp.asarray(scale * np.asarray(sys_.q_inj)[None, :],
                        jnp.float32)
        ref = jax.jit(
            jax.vmap(lambda pi, qi: solve(p_inj=pi, q_inj=qi))
        )(p, q)
        got = solve_m(p_inj=p, q_inj=q)
        assert bool(np.asarray(got.converged).all())
        # The SOLUTION path is byte-identical at any lane split; the
        # realized P/Q and residual diagnostics go through a
        # vmap-collapsed GEMM that re-tiles with the per-device row
        # count (module docstring), so they get f32-eps closeness.
        for f in ("v", "theta", "iterations", "converged"):
            assert (
                np.asarray(getattr(ref, f)).tobytes()
                == np.asarray(getattr(got, f)).tobytes()
            ), f
        for f in ("p", "q", "mismatch"):
            np.testing.assert_allclose(
                np.asarray(getattr(got, f)), np.asarray(getattr(ref, f)),
                atol=5e-5, err_msg=f,
            )
        # The lane axis really lands on every device.
        assert len(got.v.sharding.device_set) == D2
        # Indivisible lane counts: a typed error, not a wrong answer.
        if D2 > 1:
            with pytest.raises(ValueError, match="does not divide"):
                solve_m(p_inj=p[: D2 + 1])
        # The fixed-iteration variant runs too (QSTS cold starts).
        rf = solve_fixed_m(p_inj=p, q_inj=q)
        assert np.asarray(rf.v).shape == (lanes, sys_.n_bus)

    # x64 path: solutions byte-identical, derived P/Q within 1e-12
    # (the f64 GEMM re-tiling noted in the module docstring).
    solve64, _ = make_newton_solver(sys_, max_iter=8)
    solve64_m, _ = make_newton_solver(sys_, max_iter=8, mesh=lane_mesh)
    p64 = jnp.asarray(scale * np.asarray(sys_.p_inj)[None, :])
    q64 = jnp.asarray(scale * np.asarray(sys_.q_inj)[None, :])
    ref64 = jax.jit(
        jax.vmap(lambda pi, qi: solve64(p_inj=pi, q_inj=qi))
    )(p64, q64)
    got64 = solve64_m(p_inj=p64, q_inj=q64)
    np.testing.assert_array_equal(np.asarray(ref64.v), np.asarray(got64.v))
    np.testing.assert_array_equal(
        np.asarray(ref64.theta), np.asarray(got64.theta)
    )
    np.testing.assert_allclose(
        np.asarray(ref64.p), np.asarray(got64.p), rtol=1e-12, atol=1e-12
    )


@needs_mesh
def test_krylov_mesh_batched_matches_vmap(lane_mesh):
    from freedm_tpu.grid.cases import synthetic_mesh
    from freedm_tpu.pf.krylov import make_krylov_solver

    sys_ = synthetic_mesh(80, seed=4, load_mw=2.0, chord_frac=1.0)
    lanes = D2
    rng = np.random.default_rng(1)
    scale = rng.uniform(0.9, 1.1, (lanes, 1))
    with enable_x64(False):
        _, solve_fixed = make_krylov_solver(
            sys_, max_iter=6, inner_iters=12
        )
        _, solve_fixed_m = make_krylov_solver(
            sys_, max_iter=6, inner_iters=12, mesh=lane_mesh
        )
        p = jnp.asarray(scale * np.asarray(sys_.p_inj)[None, :],
                        jnp.float32)
        q = jnp.asarray(scale * np.asarray(sys_.q_inj)[None, :],
                        jnp.float32)
        ref = jax.jit(
            jax.vmap(lambda pi, qi: solve_fixed(p_inj=pi, q_inj=qi))
        )(p, q)
        got = solve_fixed_m(p_inj=p, q_inj=q)
        assert bool(np.asarray(got.converged).all())
        # Krylov's inner solve is matvec-driven, so its iterates see the
        # GEMM re-tiling directly (module docstring): the sharded lanes
        # agree to f32 eps, not bit-for-bit.
        np.testing.assert_allclose(
            np.asarray(got.v), np.asarray(ref.v), atol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(got.theta), np.asarray(ref.theta), atol=1e-5
        )


@needs_mesh
def test_ladder_mesh_batched_matches_vmap(lane_mesh):
    from freedm_tpu.grid.cases import synthetic_radial
    from freedm_tpu.pf import ladder
    from freedm_tpu.utils import cplx

    feeder = synthetic_radial(64, seed=0, load_kw=1.0)
    rng = np.random.default_rng(0)
    scale = rng.uniform(0.7, 1.3, (2 * D2, 1, 1))
    with enable_x64(False):
        _, solve_fixed = ladder.make_ladder_solver(feeder, max_iter=12)
        _, solve_fixed_m = ladder.make_ladder_solver(
            feeder, max_iter=12, mesh=lane_mesh
        )
        s = cplx.as_c(
            (scale * np.asarray(feeder.s_load)[None]).astype(np.complex64)
        )
        ref = jax.jit(jax.vmap(solve_fixed))(s)
        got = solve_fixed_m(s)
        for name in ("v_node", "i_branch", "i_load"):
            a, b = getattr(ref, name), getattr(got, name)
            assert np.asarray(a.re).tobytes() == np.asarray(b.re).tobytes()
            assert np.asarray(a.im).tobytes() == np.asarray(b.im).tobytes()
        np.testing.assert_array_equal(
            np.asarray(ref.iterations), np.asarray(got.iterations)
        )


@needs_mesh
def test_n1_mesh_screen_matches_unsharded_with_padding(lane_mesh):
    from freedm_tpu.grid.matpower import load_builtin
    from freedm_tpu.pf.n1 import make_n1_screen, secure_outages

    sys_ = load_builtin("case_ieee30")
    ks = jnp.asarray(secure_outages(sys_))
    # A lane count the mesh does NOT divide exercises the pad + slice.
    if int(ks.shape[0]) % D2 == 0:
        ks = ks[:-1]
    ref = make_n1_screen(sys_, max_iter=24)(ks)
    got = make_n1_screen(sys_, max_iter=24, mesh=lane_mesh)(ks)
    for f in ref._fields:
        assert (
            np.asarray(getattr(ref, f)).tobytes()
            == np.asarray(getattr(got, f)).tobytes()
        ), f


# ---------------------------------------------------------------------------
# QSTS: sharded == unsharded summaries/checkpoints, resume across counts
# ---------------------------------------------------------------------------

_BUS = dict(case="case14", scenarios=2 * D2, steps=8, chunk_steps=3,
            dt_minutes=15.0, seed=2)


@needs_mesh
def test_qsts_sharded_summary_is_byte_identical():
    with enable_x64(False):
        ref = run_study(StudySpec(**_BUS))
        assert ref["mesh_devices"] == 1
        got = run_study(StudySpec(mesh_devices=D2, **_BUS))
        assert got["mesh_devices"] == D2
        assert strip_timing(got) == strip_timing(ref)


def test_qsts_sharded_summary_close_in_x64():
    # The x64 cousin of the byte-identity test: everything equal except
    # the GEMM-derived loss/peak floats, pinned to 1e-12 relative.
    if D2 < 2:
        pytest.skip("single-device host")
    ref = run_study(StudySpec(**_BUS))
    got = run_study(StudySpec(mesh_devices=D2, **_BUS))
    a, b = strip_timing(ref), strip_timing(got)
    assert set(a) == set(b)
    for k in a:
        if isinstance(a[k], float):
            np.testing.assert_allclose(b[k], a[k], rtol=1e-12, err_msg=k)
        else:
            assert a[k] == b[k], k


@needs_mesh
def test_qsts_sharded_feeder_summary_is_byte_identical():
    fb = dict(case="vvc_9bus", scenarios=D2, steps=4, chunk_steps=2,
              dt_minutes=60.0, seed=1)
    with enable_x64(False):
        ref = run_study(StudySpec(**fb))
        got = run_study(StudySpec(mesh_devices=D2, **fb))
        assert strip_timing(got) == strip_timing(ref)


@needs_mesh
def test_qsts_kill_and_resume_across_device_counts(tmp_path):
    # Kill a sharded study at a chunk boundary, resume UNSHARDED (and
    # the other way around): the placement-free checkpoint makes both
    # byte-identical to the uninterrupted run.
    with enable_x64(False):
        uninterrupted = run_study(StudySpec(**_BUS))
        ck = str(tmp_path / "a.json")
        partial = run_study(StudySpec(mesh_devices=D2, **_BUS),
                            checkpoint_path=ck, stop_after_chunks=1)
        assert partial["completed"] is False
        resumed = run_study(StudySpec(**_BUS), checkpoint_path=ck)
        assert resumed["resumed_from_chunk"] == 1
        assert strip_timing(resumed) == strip_timing(uninterrupted)

        ck2 = str(tmp_path / "b.json")
        run_study(StudySpec(**_BUS), checkpoint_path=ck2,
                  stop_after_chunks=2)
        resumed2 = run_study(StudySpec(mesh_devices=D2, **_BUS),
                             checkpoint_path=ck2)
        assert resumed2["resumed_from_chunk"] == 2
        assert strip_timing(resumed2) == strip_timing(uninterrupted)


def test_qsts_scenarios_must_divide_mesh():
    if D2 < 2:
        pytest.skip("single-device host")
    from freedm_tpu.scenarios.engine import QstsEngine

    with pytest.raises(ValueError, match="does not divide"):
        QstsEngine(StudySpec(case="case14", scenarios=D2 + 1,
                             mesh_devices=D2))


def test_placement_free_spec_strips_only_mesh_keys():
    d = StudySpec(mesh_devices=4, **_BUS).to_dict()
    stripped = placement_free_spec(d)
    assert "mesh_devices" not in stripped
    assert stripped == placement_free_spec(StudySpec(**_BUS).to_dict())
    assert stripped["case"] == "case14"


def test_jobs_api_validates_mesh_devices():
    from freedm_tpu.scenarios.jobs import parse_job_request
    from freedm_tpu.serve import InvalidRequest

    spec, _ = parse_job_request({"case": "case14", "scenarios": 2 * D,
                                 "mesh_devices": -1})
    assert spec.mesh_devices == -1
    if D > 1:
        with pytest.raises(InvalidRequest, match="must divide"):
            parse_job_request({"case": "case14", "scenarios": D + 1,
                               "mesh_devices": -1})
    with pytest.raises(InvalidRequest, match="local device"):
        parse_job_request({"case": "case14", "scenarios": 4,
                           "mesh_devices": 4096})
    # The server default applies when the request omits the field.
    spec2, _ = parse_job_request({"case": "case14", "scenarios": 2 * D},
                                 default_mesh_devices=-1)
    assert spec2.mesh_devices == -1


# ---------------------------------------------------------------------------
# serve: mesh-backed engines answer identically
# ---------------------------------------------------------------------------


@needs_mesh
def test_serve_mesh_engines_match_unsharded():
    from freedm_tpu.serve import ServeConfig, Service
    from freedm_tpu.serve.service import (
        N1Request,
        PowerFlowRequest,
        VVCRequest,
    )

    buckets = (1, D2, 2 * D2)
    plain = Service(ServeConfig(max_batch=2 * D2, buckets=buckets))
    mesh = Service(ServeConfig(max_batch=2 * D2, buckets=buckets,
                               mesh_devices=D2))
    try:
        assert mesh.stats()["mesh_devices"] == D2
        for i in range(2):
            a = plain.request("pf", PowerFlowRequest(
                case="case14", scale=1.0 + 0.01 * i, return_state=True))
            b = mesh.request("pf", PowerFlowRequest(
                case="case14", scale=1.0 + 0.01 * i, return_state=True))
            assert a.v == b.v and a.residual_pu == b.residual_pu
            assert a.iterations == b.iterations
        secure = plain.engine("n1", "case_ieee30")._secure[:3]
        ra = plain.request("n1", N1Request(case="case_ieee30",
                                           outages=[int(k) for k in secure]))
        rb = mesh.request("n1", N1Request(case="case_ieee30",
                                          outages=[int(k) for k in secure]))
        assert ra.residual_pu == rb.residual_pu
        assert ra.v_min_pu == rb.v_min_pu
        veng = plain.engine("vvc", "vvc_9bus")
        q = (np.random.default_rng(0).uniform(-20, 20, (veng.nb, 3))
             * veng._mask)
        va = plain.request("vvc", VVCRequest(case="vvc_9bus", q_ctrl_kvar=q))
        vb = mesh.request("vvc", VVCRequest(case="vvc_9bus", q_ctrl_kvar=q))
        assert va.loss_kw == vb.loss_kw and va.v_min_pu == vb.v_min_pu
    finally:
        plain.stop()
        mesh.stop()


# ---------------------------------------------------------------------------
# profiling: the scale-out is observable
# ---------------------------------------------------------------------------


@needs_mesh
def test_mesh_profiling_accounts(lane_mesh):
    from freedm_tpu.core import profiling

    profiling.PROFILER.configure(enabled=True)
    try:
        run_study(StudySpec(mesh_devices=D2, **_BUS))
        snap = profiling.PROFILER.snapshot()
        assert snap["mesh_devices"].get("qsts") == D2
        # The shard/gather host boundary was timed.
        assert snap["host"].get("mesh.shard_put", {}).get("count", 0) > 0
        assert snap["host"].get("mesh.gather", {}).get("count", 0) > 0
    finally:
        profiling.PROFILER.configure(enabled=False)
        profiling.PROFILER.reset()
