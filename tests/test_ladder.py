"""Ladder power-flow solver tests.

Oracles: analytic 2-bus solutions, power-balance identities, and the
convergence envelope of the reference solver (eps=1e-4 within 20 sweeps on
its own 9-bus feeder, ``Broker/src/vvc/DPF_return7.cpp:13-15``).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from freedm_tpu.grid import cases, from_branch_table, load_dl_mat
from freedm_tpu.pf import (
    load_power_kva,
    make_ladder_solver,
    substation_power_kva,
    total_loss_kw,
    v_polar,
)
from freedm_tpu.utils import cplx
from freedm_tpu.utils.cplx import C

REF_DL_MAT = "/root/reference/Broker/Dl_new.mat"  # fixture-first via refdata


def test_9bus_converges_within_reference_envelope():
    feeder = cases.vvc_9bus()
    solve, _ = make_ladder_solver(feeder, eps=1e-4, max_iter=20)
    res = solve(feeder.s_load)
    assert bool(res.converged)
    assert int(res.iterations) <= 20
    mag, _ = v_polar(res)
    # All phases present on this feeder; voltages in a sane band.
    assert np.all(np.asarray(mag) > 0.9)
    assert np.all(np.asarray(mag) < 1.1)


def test_9bus_power_balance():
    feeder = cases.vvc_9bus()
    solve, _ = make_ladder_solver(feeder)
    res = solve(feeder.s_load)
    p_sub = float(np.sum(np.asarray(substation_power_kva(feeder, res).re)))
    p_load = float(np.sum(np.asarray(load_power_kva(feeder, res).re)))
    loss = float(total_loss_kw(feeder, res))
    # Loss identity and small positive losses for a net-load feeder.
    assert loss == pytest.approx(p_sub - p_load, abs=1e-9)
    assert 0 < loss < 50
    # Loads recovered: constant-power model must draw what the table says.
    np.testing.assert_allclose(p_load, feeder.s_load.real.sum(), rtol=1e-3)


def test_zero_load_gives_flat_voltage():
    feeder = cases.vvc_9bus()
    solve, _ = make_ladder_solver(feeder)
    res = solve(np.zeros((feeder.n_branches, 3), dtype=complex))
    mag, _ = v_polar(res)
    np.testing.assert_allclose(np.asarray(mag), feeder.v_source_pu, rtol=1e-12)
    np.testing.assert_allclose(np.asarray(res.i_branch.abs()), 0.0, atol=1e-12)


def test_two_bus_analytic():
    """V1 solves V1 = V0 - Z·conj(S/V1); compare against numpy fixed point."""
    z_codes = np.eye(3)[None] * (0.01 + 0.03j)  # ohms, decoupled phases
    p_kw, q_kvar = 300.0, 100.0
    dl = np.array([[1, 0, 1, 1, 1.0, 1, p_kw, q_kvar, p_kw, q_kvar, p_kw, q_kvar, 0]])
    feeder = from_branch_table(dl, z_codes, base_kva=1000.0, base_kv=12.47, v_source_pu=1.0)
    solve, _ = make_ladder_solver(feeder, eps=1e-10, max_iter=50)
    res = solve(feeder.s_load)
    assert bool(res.converged)

    zb = 1000.0 * 12.47**2 / 1000.0
    z_pu = (0.01 + 0.03j) / zb
    s_pu = (p_kw + 1j * q_kvar) / (1000.0 / 3.0)
    v = 1.0 + 0j
    for _ in range(200):
        v = 1.0 - z_pu * np.conj(s_pu / v)
    got = res.v_node.to_numpy()[1, 0]  # phase a
    np.testing.assert_allclose(got, v, rtol=1e-8)


def test_missing_phase_masks_voltage():
    # Branch 2 carries only phase a (codes: 3-phase, then single-phase).
    z3 = np.full((3, 3), 0.3 + 0.9j) + np.eye(3) * (0.6 + 1.4j)
    z1 = np.zeros((3, 3), dtype=complex)
    z1[0, 0] = 0.9 + 2.3j
    dl = np.array(
        [
            [1, 0, 1, 1, 1.0, 1, 10, 2, 10, 2, 10, 2, 0],
            [2, 1, 2, 2, 1.0, 1, 5, 1, 5, 1, 5, 1, 0],
        ]
    )
    feeder = from_branch_table(dl, np.stack([z3, z1]))
    assert feeder.phase_mask.tolist() == [[1, 1, 1], [1, 0, 0]]
    solve, _ = make_ladder_solver(feeder)
    res = solve(feeder.s_load)
    v = res.v_node.to_numpy()
    assert abs(v[2, 1]) == 0 and abs(v[2, 2]) == 0
    assert abs(v[2, 0]) > 0.9


def test_reference_dl_new_mat_loads_and_converges():
    """The Dl format ships line-code indices without the impedance library
    (see load_dl_mat), so this checks loader + solver plumbing on the
    reference's own saved table, at a loading feasible for the synthesized
    generic line codes."""
    from refdata import resolve

    feeder = load_dl_mat(resolve("Dl_new.mat", REF_DL_MAT))
    assert feeder.n_branches == 33  # 33 real branches among the 41 rows
    solve, _ = make_ladder_solver(feeder, max_iter=60)
    res = solve(0.5 * feeder.s_load)
    assert bool(res.converged)
    assert float(jnp.min(res.v_node.abs())) > 0.5


def test_vmap_over_scenarios():
    feeder = cases.vvc_9bus()
    _, solve_fixed = make_ladder_solver(feeder, max_iter=25)
    scales = np.linspace(0.2, 1.2, 8)
    loads = cplx.as_c(scales[:, None, None] * feeder.s_load)
    batched = jax.vmap(solve_fixed)(loads)
    assert batched.v_node.shape == (8, feeder.n_nodes, 3)
    # Heavier load -> lower minimum voltage, monotonically.
    vmin = np.asarray(jnp.min(batched.v_node.abs(), axis=(1, 2)))
    assert np.all(np.diff(vmin) < 0)


def test_fixed_solver_matches_while_loop():
    feeder = cases.vvc_9bus()
    solve, solve_fixed = make_ladder_solver(feeder, eps=1e-12, max_iter=40)
    r1 = solve(feeder.s_load)
    r2 = solve_fixed(feeder.s_load)
    np.testing.assert_allclose(r1.v_node.to_numpy(), r2.v_node.to_numpy(), atol=1e-10)


def test_gradient_matches_finite_difference():
    """d loss / d Q via autodiff through the fixed-iteration solver —
    the jax.grad replacement for the reference's hand-built adjoint
    (VoltVarCtrl.cpp:1222-1309)."""
    feeder = cases.vvc_9bus()
    _, solve_fixed = make_ladder_solver(feeder, max_iter=30)
    p0 = jnp.asarray(feeder.s_load.real)

    def loss_of_q(q):
        return total_loss_kw(feeder, solve_fixed(C(p0, q)))

    q = jnp.zeros((feeder.n_branches, 3))
    g = jax.grad(loss_of_q)(q)
    h = 1e-3
    for idx in [(1, 0), (4, 2), (6, 1)]:
        e = jnp.zeros_like(q).at[idx].set(h)
        fd = (loss_of_q(q + e) - loss_of_q(q - e)) / (2 * h)
        np.testing.assert_allclose(np.asarray(g[idx]), np.asarray(fd), rtol=1e-4, atol=1e-7)


# Solved per-unit voltage profile of the reference's own 9-bus feeder
# (load_system_data.cpp constants, balanced loads, Vsrc = 1.015 pu),
# converged to eps=1e-12.  Cross-validated at 1e-8 against the
# independent current-injection solver (tests/test_cim.py), whose fixed
# point is derived from the assembled 3x3-block Ybus and shares no
# iteration code with the ladder — a systematic per-unit scaling error
# consistent with power balance cannot pass both.  VERDICT r4 item 7:
# parity is numbers, not envelopes.
VMAG_9BUS = [
    1.015, 1.00939711, 1.0040465, 1.00119821, 0.99744601,
    0.99594453, 1.00527471, 1.00378899, 1.00154268,
]
VANG_A_DEG_9BUS = [
    0.0, -1.23164922, -2.05637049, -2.49655225, -3.10376139,
    -3.35122193, -1.88576639, -2.12126044, -2.48804538,
]
LOSS_KW_9BUS = 11.674965
SUB_P_KVA_9BUS = 308.891655  # per phase
SUB_Q_KVA_9BUS = 13.630167


def test_9bus_value_level_solution_pin():
    """The computed solution itself, pinned to frozen numbers (1e-6):
    magnitudes, phase-a angles, total loss, and substation P/Q."""
    from freedm_tpu.pf.ladder import substation_power_kva, v_polar

    feeder = cases.vvc_9bus()
    solve, _ = make_ladder_solver(feeder, eps=1e-12, max_iter=200)
    r = solve(feeder.s_load)
    assert bool(r.converged)
    mag, ang = v_polar(r)
    mag, ang = np.asarray(mag), np.asarray(ang)
    np.testing.assert_allclose(mag[:, 0], VMAG_9BUS, atol=1e-6)
    # Balanced loads: phases b/c mirror a, displaced exactly +-120 deg.
    np.testing.assert_allclose(mag[:, 1], VMAG_9BUS, atol=1e-6)
    np.testing.assert_allclose(ang[:, 0], VANG_A_DEG_9BUS, atol=1e-5)
    np.testing.assert_allclose(
        ang[:, 1], np.asarray(VANG_A_DEG_9BUS) - 120.0, atol=1e-5
    )
    np.testing.assert_allclose(
        float(total_loss_kw(feeder, r)), LOSS_KW_9BUS, atol=1e-4
    )
    s = substation_power_kva(feeder, r)
    np.testing.assert_allclose(np.asarray(s.re), SUB_P_KVA_9BUS, atol=1e-4)
    np.testing.assert_allclose(np.asarray(s.im), SUB_Q_KVA_9BUS, atol=1e-4)
