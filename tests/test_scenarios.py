"""QSTS scenario subsystem tests (``freedm_tpu.scenarios``): generator
and profile determinism (the resume-correctness bedrock), the chunked
engine's summaries and warm-start savings, exact checkpoint resume, and
the async jobs API (in-process and over HTTP)."""

import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from freedm_tpu.grid.cases import synthetic_mesh, synthetic_radial
from freedm_tpu.scenarios.engine import StudySpec, run_study, strip_timing
from freedm_tpu.scenarios.jobs import JobManager, parse_job_request
from freedm_tpu.scenarios.profiles import ProfileSet, ProfileSpec
from freedm_tpu.serve import InvalidRequest, NotFound

# ---------------------------------------------------------------------------
# generator determinism: same seed => byte-identical cases/profiles
# ---------------------------------------------------------------------------


def test_synthetic_radial_same_seed_is_byte_identical():
    a = synthetic_radial(40, seed=9)
    b = synthetic_radial(40, seed=9)
    for name in ("s_load", "z_pu", "parent", "phase_mask"):
        av, bv = np.asarray(getattr(a, name)), np.asarray(getattr(b, name))
        assert av.tobytes() == bv.tobytes(), name
    c = synthetic_radial(40, seed=10)
    assert np.asarray(a.s_load).tobytes() != np.asarray(c.s_load).tobytes()


def test_synthetic_mesh_same_seed_is_byte_identical():
    a = synthetic_mesh(60, seed=9)
    b = synthetic_mesh(60, seed=9)
    for name in ("bus_type", "p_inj", "q_inj", "v_set", "from_bus",
                 "to_bus", "r", "x", "b_chg"):
        av, bv = np.asarray(getattr(a, name)), np.asarray(getattr(b, name))
        assert av.tobytes() == bv.tobytes(), name
    c = synthetic_mesh(60, seed=10)
    assert np.asarray(a.p_inj).tobytes() != np.asarray(c.p_inj).tobytes()


def test_profiles_identical_regardless_of_chunking():
    spec = ProfileSpec(scenarios=5, steps=96, dt_minutes=15.0, seed=4,
                       kind="mixed")
    ps = ProfileSet(spec, 23)
    full_l, full_p = ps.chunk(0, 96)
    # Any chunking reproduces the same tensors byte-for-byte — the half
    # of the resume contract the profile model owns.
    for cuts in ((0, 96), (0, 24, 96), (0, 7, 50, 96)):
        parts_l = [ps.load_chunk(a, b) for a, b in zip(cuts, cuts[1:])]
        parts_p = [ps.pv_chunk(a, b) for a, b in zip(cuts, cuts[1:])]
        assert np.concatenate(parts_l, axis=1).tobytes() == full_l.tobytes()
        assert np.concatenate(parts_p, axis=1).tobytes() == full_p.tobytes()
    # A fresh set from the same spec agrees; a different seed does not.
    again_l, again_p = ProfileSet(spec, 23).chunk(0, 96)
    assert again_l.tobytes() == full_l.tobytes()
    assert again_p.tobytes() == full_p.tobytes()
    other = ProfileSet(
        ProfileSpec(scenarios=5, steps=96, dt_minutes=15.0, seed=5,
                    kind="mixed"), 23)
    assert other.load_chunk(0, 96).tobytes() != full_l.tobytes()


def test_profiles_are_lazy_and_physical():
    ps = ProfileSet(ProfileSpec(scenarios=3, steps=96, seed=1), 10)
    load = ps.load_chunk(10, 20)
    pv = ps.pv_chunk(10, 20)
    assert load.shape == (3, 10, 10) and pv.shape == (3, 10, 10)
    assert np.all(load > 0)  # a night valley still draws something
    assert np.all(pv >= 0)
    # PV is zero at night (t=0 is midnight at dt=15min).
    assert np.all(ps.pv_chunk(0, 4) == 0.0)


# ---------------------------------------------------------------------------
# engine: summaries, warm starts, exact resume
# ---------------------------------------------------------------------------

_SPEC = dict(case="case14", scenarios=3, steps=8, chunk_steps=3,
             dt_minutes=15.0, seed=2)


_strip_timing = strip_timing  # the engine's own comparison view


def test_bus_study_summary_and_warm_start_savings():
    warm = run_study(StudySpec(**_SPEC))
    assert warm["completed"] and warm["solver"] == "newton"
    assert warm["lane_steps_not_converged"] == 0
    assert warm["energy_balance_ok"]
    assert np.isfinite(warm["violation_bus_minutes_mean"])
    assert 0.5 < warm["v_min_pu"] <= warm["v_max_pu"] < 1.2
    assert warm["energy_loss_mwh_mean"] > 0
    assert warm["peak_branch_mva"] > 0
    # One jitted program per chunk shape: 8 steps in chunks of 3 is two
    # shapes (3 and the ragged 2).
    assert warm["compiles"] == 2
    cold = run_study(StudySpec(warm_start=False, **_SPEC))
    assert cold["iters_mean"] > warm["iters_mean"]


def test_feeder_study_summary():
    s = run_study(StudySpec(case="vvc_9bus", scenarios=2, steps=4,
                            chunk_steps=2, dt_minutes=60.0, seed=1))
    assert s["completed"] and s["solver"] == "ladder"
    assert s["warm_start"] is False  # the ladder has no warm-start surface
    assert s["lane_steps_not_converged"] == 0
    assert s["energy_balance_ok"]
    assert s["energy_loss_kwh_mean"] > 0 and s["peak_branch_kva"] > 0


def test_resume_from_chunk_checkpoint_is_exact(tmp_path):
    ck = str(tmp_path / "study.json")
    spec = StudySpec(**_SPEC)
    partial = run_study(spec, checkpoint_path=ck, stop_after_chunks=1)
    assert partial["completed"] is False and partial["chunks_done"] == 1
    resumed = run_study(spec, checkpoint_path=ck)
    assert resumed["resumed_from_chunk"] == 1
    uninterrupted = run_study(spec)
    assert _strip_timing(resumed) == _strip_timing(uninterrupted)


def test_mismatched_checkpoint_spec_restarts_clean(tmp_path):
    ck = str(tmp_path / "study.json")
    run_study(StudySpec(**_SPEC), checkpoint_path=ck,
              stop_after_chunks=1)
    other = StudySpec(**{**_SPEC, "seed": 3})
    s = run_study(other, checkpoint_path=ck)
    assert s["resumed_from_chunk"] == 0 and s["completed"]


# ---------------------------------------------------------------------------
# jobs API: validation, lifecycle, HTTP wiring
# ---------------------------------------------------------------------------


def test_parse_job_request_is_typed():
    spec, key = parse_job_request({"case": "case14", "scenarios": 2,
                                   "job_key": "a-b.c_1"})
    assert spec.case == "case14" and key == "a-b.c_1"
    for bad in (
        {"scenarios": 2},  # missing case
        {"case": "case14", "frobnicate": 1},  # unknown field
        {"case": "case14", "scenarios": 0},
        {"case": "case14", "scenarios": "many"},
        {"case": "case14", "steps": 10**9},
        {"case": "case14", "dt_minutes": -1.0},
        {"case": "case14", "profile": "lunar"},
        {"case": "case14", "warm_start": "yes"},
        {"case": "case14", "job_key": "../escape"},
        {"case": "no_such_case"},
        {"case": "mesh2000", "scenarios": 1024},  # lane-cell ceiling
    ):
        with pytest.raises(InvalidRequest):
            parse_job_request(bad)


def _wait_terminal(jm, job_id, timeout_s=120.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        j = jm.get(job_id)
        if j["state"] in ("completed", "failed", "cancelled"):
            return j
        time.sleep(0.1)
    return jm.get(job_id)


def test_job_manager_lifecycle_resume_and_cancel(tmp_path):
    jm = JobManager(workers=1, checkpoint_dir=str(tmp_path)).start()
    try:
        payload = {"case": "vvc_9bus", "scenarios": 2, "steps": 4,
                   "chunk_steps": 2, "dt_minutes": 60.0, "job_key": "t1"}
        d = jm.submit(payload)
        assert d["state"] == "queued" and d["chunks_total"] == 2
        j = _wait_terminal(jm, d["job_id"])
        assert j["state"] == "completed", j.get("error")
        assert j["summary"]["energy_balance_ok"]
        assert (tmp_path / "qsts_t1.json").exists()
        # Resubmitting the identical keyed spec resumes (here: from the
        # final chunk — the summary must match the first run exactly).
        d2 = jm.submit(payload)
        j2 = _wait_terminal(jm, d2["job_id"])
        assert j2["state"] == "completed"
        assert j2["summary"]["resumed_from_chunk"] == 2
        assert _strip_timing(j2["summary"]) == _strip_timing(j["summary"])
        # Unknown ids are typed.
        with pytest.raises(NotFound):
            jm.get("nope")
        with pytest.raises(NotFound):
            jm.cancel("nope")
        # Cancelling a terminal job is a no-op on its state.
        assert jm.cancel(d2["job_id"])["state"] == "completed"
        # A failing study surfaces as state=failed, never a raise.
        bad = jm.submit({"case": "case14", "scenarios": 1, "steps": 2,
                         "chunk_steps": 2, "max_iter": 1})
        jf = _wait_terminal(jm, bad["job_id"])
        assert jf["state"] in ("completed", "failed")
    finally:
        jm.stop()


def test_jobs_http_roundtrip(tmp_path):
    from freedm_tpu.serve import ServeConfig, ServeServer, Service

    svc = Service(ServeConfig(max_batch=2, buckets=(1, 2)), start=False)
    jm = JobManager(workers=1, checkpoint_dir=str(tmp_path)).start()
    srv = ServeServer(svc, port=0, jobs=jm).start()
    try:
        body = json.dumps({"case": "vvc_9bus", "scenarios": 2, "steps": 4,
                           "chunk_steps": 2, "dt_minutes": 60.0}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/v1/qsts", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            assert r.status == 202
            d = json.loads(r.read())
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/v1/jobs/{d['job_id']}",
                timeout=10,
            ) as r:
                j = json.loads(r.read())
            if j["state"] in ("completed", "failed"):
                break
            time.sleep(0.2)
        assert j["state"] == "completed", j.get("error")
        assert np.isfinite(j["summary"]["violation_bus_minutes_mean"])
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/v1/jobs/deadbeef", timeout=10)
        with ei.value:
            assert ei.value.code == 404
            assert json.loads(ei.value.read())["error"]["type"] == "not_found"
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/healthz", timeout=10
        ) as r:
            assert json.loads(r.read())["qsts"] is True
    finally:
        srv.stop()
        jm.stop()
        svc.stop()


# ---------------------------------------------------------------------------
# GL006 confirmation: observed lock order vs the static lock graph
# ---------------------------------------------------------------------------


def test_debuglock_jobmanager_order_confirms_gl006_static_graph():
    # The jobs table bumps qsts_jobs_total{cancelled} while holding its
    # condition (cancel of a still-queued job).  Instrument both locks
    # with GL006-named DebugLocks, exercise the path, and assert the
    # observed order composes acyclically with the static lock graph.
    import pathlib
    import threading

    from freedm_tpu.core import metrics as obs
    from freedm_tpu.core.debuglock import DebugLock, LockOrderRecorder
    from freedm_tpu.scenarios.jobs import JobManager
    from freedm_tpu.tools.gridlint import run_lint

    rec = LockOrderRecorder()
    cond_name = "freedm_tpu/scenarios/jobs.py:JobManager._cond"
    metric_name = "freedm_tpu/core/metrics.py:_Metric._lock"
    counter = obs.QSTS_JOBS
    old_lock = counter._lock
    dbg_metric = DebugLock(metric_name, recursive=True, recorder=rec)
    # Deliberately NOT started: the submitted job stays queued, so
    # cancel() settles it inline — under the instrumented condition.
    jm = JobManager(workers=1)
    jm._cond = threading.Condition(lock=DebugLock(cond_name, recorder=rec))
    try:
        counter._lock = dbg_metric
        for child in counter._children.values():
            child._lock = dbg_metric
        job = jm.submit({"case": "case14", "scenarios": 2, "steps": 4})
        out = jm.cancel(job["job_id"])
        assert out["state"] == "cancelled"
    finally:
        counter._lock = old_lock
        for child in counter._children.values():
            child._lock = old_lock

    observed = rec.snapshot_edges()
    assert (cond_name, metric_name) in observed
    assert (metric_name, cond_name) not in observed

    root = pathlib.Path(__file__).resolve().parent.parent
    # The modules holding every lock these edges can touch (scanning
    # the subset keeps the static pass fast inside tier-1).
    static = run_lint(
        [str(root / "freedm_tpu" / d) for d in ("serve", "scenarios", "core")],
        root=str(root),
    )
    static_edges = {
        tuple(e) for e in static.artifacts["lock_graph"]["edges"]
    }
    # The cancel-path edge is exactly what GL006 derives statically.
    assert (cond_name, metric_name) in static_edges
    assert LockOrderRecorder.find_cycle(observed | static_edges) is None
