"""Config stack tests: cfg parsing, Timings strictness (reference: CTimings
hard-fails on missing keys), GlobalConfig from freedm.cfg-format files."""

import pytest

from freedm_tpu.core import GlobalConfig, Timings, parse_cfg

REF_TIMINGS = "/root/reference/Broker/config/timings.cfg"


def test_parse_reference_timings_cfg():
    from refdata import resolve

    t = Timings.from_file(resolve("timings.cfg", REF_TIMINGS))
    assert t.gm_phase_time == 530
    assert t.sc_phase_time == 320
    assert t.lb_phase_time == 4100
    assert t.lb_round_time == 3000
    assert t.lb_request_timeout == 140
    assert t.csrc_resend_time == 60
    assert t.dev_rtds_delay == 50
    # Full published round: 530+320+4100+4100 = 9050 ms (BASELINE.md).
    assert t.round_length_ms() == 9050


def test_timings_strict_missing_key(tmp_path):
    p = tmp_path / "t.cfg"
    p.write_text("GM_PHASE_TIME = 100\n")
    with pytest.raises(ValueError, match="missing required"):
        Timings.from_file(p)
    t = Timings.from_file(p, strict=False)
    assert t.gm_phase_time == 100
    assert t.sc_phase_time == 320  # default retained


def test_timings_unknown_key(tmp_path):
    p = tmp_path / "t.cfg"
    p.write_text("BOGUS_TIME = 5\n")
    with pytest.raises(ValueError, match="unknown timing"):
        Timings.from_file(p, strict=False)


def test_global_config_from_file(tmp_path):
    p = tmp_path / "freedm.cfg"
    p.write_text(
        """
# comment
address=0.0.0.0
port=51870
add-host=alpha.freedm:51870
add-host=beta.freedm:51870
verbose=5
migration-step = 2
malicious-behavior = 1
mqtt-subscribe=SST
mqtt-subscribe=DESD
"""
    )
    cfg = GlobalConfig.from_file(p, hostname="gamma.freedm")
    assert cfg.uuid == "gamma.freedm:51870"
    assert cfg.add_host == ["alpha.freedm:51870", "beta.freedm:51870"]
    assert cfg.migration_step == 2.0
    assert cfg.malicious_behavior is True
    assert cfg.mqtt_subscribe == ["SST", "DESD"]


def test_parse_cfg_malformed(tmp_path):
    p = tmp_path / "bad.cfg"
    p.write_text("no equals sign here\n")
    with pytest.raises(ValueError, match="malformed"):
        parse_cfg(p)
