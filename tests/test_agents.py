"""Grid-edge agent populations (docs/agents.md): per-kind step physics
vs plain-Python oracles, construction determinism, closed-loop vs
replayed divergence, SIGKILL resume, mesh-vs-vmap byte identity, and
the typed validation surfaces.

Mesh sizing note: the byte-identity halves run at S = 2·D2 (local
batch >= 2) — at local batch 1 the CPU backend's vectorization
re-tiles and even the agent-free engine moves by ~eps (see
tests/test_mesh.py's module docstring for the same constraint).
"""

import math
import os
import signal
import subprocess
import sys
import time
from dataclasses import replace

import jax
import numpy as np
import pytest
from jax.experimental import enable_x64

from freedm_tpu.scenarios.agents import (
    AgentSpec,
    AMB_MEAN_C,
    AMB_PEAK_H,
    AMB_SWING_C,
    DR_TAU_H,
    EV_V_FULL,
    EV_V_MIN,
    build_population,
    dr_signal,
    dr_step,
    ev_step,
    inverter_step,
    parse_agents_field,
    population_step,
    thermostat_step,
    validate_agent_spec,
)
from freedm_tpu.scenarios.engine import StudySpec, run_study, strip_timing
from freedm_tpu.scenarios.jobs import parse_job_request
from freedm_tpu.scenarios.profiles import ProfileSet, ProfileSpec
from freedm_tpu.serve import InvalidRequest

D = jax.local_device_count()
D2 = max(d for d in (1, 2, 4) if d <= D and D % d == 0)
needs_mesh = pytest.mark.skipif(D2 < 2, reason="single-device host")

_AGENTS = AgentSpec(ev=12, thermostat=10, inverter=8, dr=6)
_SPEC = dict(case="case14", scenarios=4, steps=12, dt_minutes=60.0,
             chunk_steps=4, seed=7)


@pytest.fixture(scope="module")
def small_world():
    """A (profiles, population) pair on a 6-bus synthetic injection."""
    profiles = ProfileSet(ProfileSpec(scenarios=2, steps=8, seed=5), 6)
    p0 = np.array([-1.0, -0.5, 0.0, -2.0, -0.3, 0.2])
    pop, state0, events = build_population(_AGENTS, profiles, p0)
    return profiles, p0, pop, state0, events


# ---------------------------------------------------------------------------
# per-kind step oracles: the jax steps vs independent plain-Python math
# ---------------------------------------------------------------------------


def _row(prm, i):
    return type(prm)(*(np.asarray(f)[i] for f in prm))


def _ev_ref(soc, v, h, prm, dt):
    if prm.arr_h <= prm.dep_h:
        present = prm.arr_h <= h < prm.dep_h
    else:
        present = h >= prm.arr_h or h < prm.dep_h
    droop = min(max((v - EV_V_MIN) / (EV_V_FULL - EV_V_MIN), 0.0), 1.0)
    p_chg = prm.rate_pu * droop * (1.0 if present and soc < 1.0 else 0.0)
    soc_next = min(soc + p_chg * dt / prm.cap_puh, 1.0) if present \
        else prm.soc0
    return soc_next, -p_chg, 0.0


def test_ev_step_matches_python_oracle(small_world):
    _, _, pop, _, _ = small_world
    dt = 0.25
    for i in range(pop.ev.bus.shape[0]):
        prm = _row(pop.ev, i)
        # Sample hours inside/outside the session window and voltages
        # across the droop: full rate, partial, fully shed.
        for h in (0.0, prm.arr_h, (prm.arr_h + 1.0) % 24.0,
                  (prm.dep_h + 1.0) % 24.0):
            for v in (1.0, 0.91, 0.8):
                for soc in (0.3, 1.0):
                    got = ev_step(soc, v, h, prm, dt)
                    want = _ev_ref(soc, v, h, prm, dt)
                    np.testing.assert_allclose(
                        [float(x) for x in got], want, rtol=1e-12,
                        err_msg=f"agent {i} h={h} v={v} soc={soc}")


def _th_ref(temp, on, h, prm, dt):
    if temp > prm.set_c + 0.5 * prm.db_c:
        on_next = 1.0
    elif temp < prm.set_c - 0.5 * prm.db_c:
        on_next = 0.0
    else:
        on_next = on
    amb = AMB_MEAN_C + prm.amb_off_c + AMB_SWING_C * math.cos(
        2.0 * math.pi * (h - AMB_PEAK_H) / 24.0)
    a = math.exp(-dt / prm.tau_h)
    temp_next = amb + (temp - amb) * a - prm.gain_c * (1.0 - a) * on_next
    return temp_next, on_next, -prm.p_pu * on_next


def test_thermostat_step_matches_python_oracle(small_world):
    _, _, pop, _, _ = small_world
    dt = 0.25
    for i in range(pop.th.bus.shape[0]):
        prm = _row(pop.th, i)
        # Above band (must switch on), below band (off), inside the
        # deadband with both relay histories (hysteresis holds).
        cases = [(prm.set_c + prm.db_c, 0.0), (prm.set_c - prm.db_c, 1.0),
                 (prm.set_c, 0.0), (prm.set_c, 1.0)]
        for temp, on in cases:
            for h in (3.0, 15.0):
                (t2, on2), p, q = thermostat_step(temp, on, 1.0, h, prm, dt)
                wt, won, wp = _th_ref(temp, on, h, prm, dt)
                np.testing.assert_allclose(
                    [float(t2), float(on2), float(p), float(q)],
                    [wt, won, wp, 0.0], rtol=1e-12,
                    err_msg=f"agent {i} temp={temp} on={on} h={h}")
                if temp == prm.set_c:
                    assert float(on2) == on  # deadband holds the relay


def _inv_ref(q, v, prm, dt):
    rise = min(max((prm.v2 - v) / (prm.v2 - prm.v1), 0.0), 1.0)
    fall = min(max((v - prm.v3) / (prm.v4 - prm.v3), 0.0), 1.0)
    q_tgt = prm.qmax_pu * (rise - fall)
    return q + (1.0 - math.exp(-dt / prm.tau_h)) * (q_tgt - q)


def test_inverter_step_matches_python_oracle(small_world):
    _, _, pop, _, _ = small_world
    dt = 0.25
    for i in range(pop.inv.bus.shape[0]):
        prm = _row(pop.inv, i)
        mid_rise = 0.5 * (prm.v1 + prm.v2)
        mid_fall = 0.5 * (prm.v3 + prm.v4)
        for v in (prm.v1 - 0.02, mid_rise, 1.0, mid_fall, prm.v4 + 0.02):
            for q in (0.0, 0.5 * prm.qmax_pu):
                q2, p, qi = inverter_step(q, v, 12.0, prm, dt)
                want = _inv_ref(q, v, prm, dt)
                np.testing.assert_allclose(float(q2), want, rtol=1e-12)
                assert float(p) == 0.0 and float(qi) == float(q2)
        # Curve shape: deep undervoltage asymptotes to +qmax, deep
        # overvoltage to -qmax, deadband target is zero.
        q_lo = _inv_ref(0.0, prm.v1 - 0.1, prm, 1e9)
        q_hi = _inv_ref(0.0, prm.v4 + 0.1, prm, 1e9)
        np.testing.assert_allclose(q_lo, prm.qmax_pu, rtol=1e-9)
        np.testing.assert_allclose(q_hi, -prm.qmax_pu, rtol=1e-9)


def _dr_ref(eng, sig, prm, dt):
    eng2 = eng + (1.0 - math.exp(-dt / DR_TAU_H)) * (sig * prm.comply - eng)
    return eng2, -prm.p_pu * (1.0 - prm.depth * eng2)


def test_dr_step_matches_python_oracle(small_world):
    _, _, pop, _, _ = small_world
    dt = 0.25
    for i in range(pop.dr.bus.shape[0]):
        prm = _row(pop.dr, i)
        for sig in (0.0, 1.0):
            for eng in (0.0, 0.4, 1.0):
                e2, p, q = dr_step(eng, sig, 12.0, prm, dt)
                we, wp = _dr_ref(eng, sig, prm, dt)
                np.testing.assert_allclose(
                    [float(e2), float(p), float(q)], [we, wp, 0.0],
                    rtol=1e-12)
        if not prm.comply:
            # Non-compliant agents never engage.
            e2, p, _ = dr_step(0.0, 1.0, 12.0, prm, dt)
            assert float(e2) == 0.0


def test_population_step_aggregates_per_bus(small_world):
    """segment_sum aggregation == a plain-Python per-bus accumulation
    of the same per-agent injections."""
    _, _, pop, state0, _ = small_world
    n_bus, dt, h, sig = 6, 0.25, 18.5, 1.0
    obs_v = np.linspace(0.9, 1.06, n_bus)
    ag2, p_bus, q_bus, served, q_peak = population_step(
        pop, state0, obs_v, sig, h, dt, n_bus)
    wp = np.zeros(n_bus)
    wq = np.zeros(n_bus)
    for i in range(pop.ev.bus.shape[0]):
        prm = _row(pop.ev, i)
        _, p, _ = _ev_ref(state0.ev_soc[i], obs_v[prm.bus], h, prm, dt)
        wp[prm.bus] += p
    for i in range(pop.th.bus.shape[0]):
        prm = _row(pop.th, i)
        _, _, p = _th_ref(state0.th_temp[i], state0.th_on[i], h, prm, dt)
        wp[prm.bus] += p
    q_abs = []
    for i in range(pop.inv.bus.shape[0]):
        prm = _row(pop.inv, i)
        q = _inv_ref(state0.inv_q[i], obs_v[prm.bus], prm, dt)
        wq[prm.bus] += q
        q_abs.append(abs(q))
    for i in range(pop.dr.bus.shape[0]):
        prm = _row(pop.dr, i)
        _, p = _dr_ref(state0.dr_eng[i], sig, prm, dt)
        wp[prm.bus] += p
    np.testing.assert_allclose(np.asarray(p_bus), wp, rtol=1e-12)
    np.testing.assert_allclose(np.asarray(q_bus), wq, rtol=1e-12)
    np.testing.assert_allclose(float(served), -wp.sum(), rtol=1e-12)
    np.testing.assert_allclose(float(q_peak), max(q_abs), rtol=1e-12)


# ---------------------------------------------------------------------------
# construction determinism
# ---------------------------------------------------------------------------


def test_same_seed_builds_byte_identical_population(small_world):
    profiles, p0, pop, state0, events = small_world
    pop2, state2, events2 = build_population(_AGENTS, profiles, p0)
    for a, b in ((pop, pop2), (state0, state2), (events, events2)):
        for x, y in zip(jax.tree_util.tree_leaves(a),
                        jax.tree_util.tree_leaves(b)):
            assert np.asarray(x).tobytes() == np.asarray(y).tobytes()
    # A different seed moves the draws.
    other = ProfileSet(ProfileSpec(scenarios=2, steps=8, seed=6), 6)
    pop3, _, _ = build_population(_AGENTS, other, p0)
    assert pop3.ev.arr_h.tobytes() != pop.ev.arr_h.tobytes()
    # Attaching agents never perturbs the profile bytes: the agent
    # stream is independent of the profile draws (population_rng seam).
    again = ProfileSet(ProfileSpec(scenarios=2, steps=8, seed=5), 6)
    assert again.scale.tobytes() == profiles.scale.tobytes()


def test_dr_signal_is_pure_in_index_and_wraps(small_world):
    profiles, _, _, _, events = small_world
    h_all = profiles.hours(0, 8)
    sig_all = dr_signal(events, h_all)
    # Chunked evaluation is byte-identical to the full window.
    sig_chunks = np.concatenate([
        dr_signal(events, profiles.hours(0, 3)),
        dr_signal(events, profiles.hours(3, 8)),
    ])
    assert sig_all.tobytes() == sig_chunks.tobytes()
    # A window straddling midnight is active on both sides.
    from freedm_tpu.scenarios.agents import DrEvents

    ev = DrEvents(start_h=np.array([[23.5]]), dur_h=np.array([[1.0]]))
    sig = dr_signal(ev, np.array([23.0, 23.75, 0.25, 0.75]))
    assert sig[:, 0].tolist() == [0.0, 1.0, 1.0, 0.0]


# ---------------------------------------------------------------------------
# typed validation surfaces
# ---------------------------------------------------------------------------


def test_validate_agent_spec_is_typed():
    validate_agent_spec(AgentSpec(ev=1))
    for bad in (
        AgentSpec(),                       # empty population
        AgentSpec(ev=-1),
        AgentSpec(ev=True),                # bool is not a count
        AgentSpec(ev=1, dr_events=9),      # past MAX_DR_EVENTS
        AgentSpec(ev=1, ev_frac=1.5),
        AgentSpec(ev=1, dr_depth=-0.1),
        AgentSpec(ev=1, closed_loop=1),    # not a bool
    ):
        with pytest.raises(ValueError):
            validate_agent_spec(bad)


def test_parse_agents_field_is_typed():
    spec = parse_agents_field({"ev": 3, "closed_loop": False}, 2,
                              max_agents=100, max_cells=1000)
    assert spec.ev == 3 and spec.closed_loop is False
    for bad in (
        "not-an-object",
        {"evs": 3},                        # unknown field
        {"ev": "three"},                   # constructor TypeError
        {"ev": 0},                         # empty population
        {"ev": 200},                       # over max_agents
        {"ev": 90},                        # 2 * 90 > max_cells=100
    ):
        with pytest.raises(InvalidRequest):
            parse_agents_field(bad, 2, max_agents=100, max_cells=100)


def test_jobs_api_threads_agents_spec():
    spec, _ = parse_job_request({"case": "case14", "scenarios": 2,
                                 "steps": 8, "agents": {"ev": 5}})
    assert spec.agents.ev == 5
    d = spec.to_dict()
    assert isinstance(d["agents"], dict)
    assert StudySpec.from_dict(d) == spec  # checkpoint-identity roundtrip
    with pytest.raises(InvalidRequest):
        parse_job_request({"case": "vvc_9bus", "scenarios": 2, "steps": 8,
                           "agents": {"ev": 5}})  # feeder case


def test_engine_rejects_feeder_case():
    with pytest.raises(ValueError, match="bus case"):
        run_study(StudySpec(case="vvc_9bus", scenarios=2, steps=4,
                            chunk_steps=2, agents=AgentSpec(ev=2)))


# ---------------------------------------------------------------------------
# closed-loop studies: summary, divergence, resume
# ---------------------------------------------------------------------------


def test_agent_summary_stamped_and_chunking_invariant():
    s = run_study(StudySpec(agents=_AGENTS, **_SPEC))
    assert s["agents_total"] == _AGENTS.total()
    assert s["agents_closed_loop"] is True
    assert s["agent_energy_puh_mean"] > 0
    assert s["agent_steps_per_sec"] > 0
    assert s["lane_steps_not_converged"] == 0
    # Different chunking, identical physics (chunk counts aside).
    other = run_study(StudySpec(agents=_AGENTS,
                                **{**_SPEC, "chunk_steps": 5}))
    drop = ("chunks_total", "compiles")
    a = {k: v for k, v in strip_timing(s).items() if k not in drop}
    b = {k: v for k, v in strip_timing(other).items() if k not in drop}
    assert a == b


def test_closed_loop_diverges_from_replayed():
    closed = run_study(StudySpec(agents=_AGENTS, **_SPEC))
    replayed = run_study(StudySpec(
        agents=replace(_AGENTS, closed_loop=False), **_SPEC))
    assert replayed["agents_closed_loop"] is False
    # The flat 1.0 pu observation sits in every inverter's deadband.
    assert replayed["agent_q_peak_pu"] == 0.0
    assert closed["agent_q_peak_pu"] > 0.0
    assert closed["energy_loss_mwh_mean"] != replayed["energy_loss_mwh_mean"]


def test_resume_from_chunk_checkpoint_is_exact(tmp_path):
    ck = str(tmp_path / "study.json")
    spec = StudySpec(agents=_AGENTS, **_SPEC)
    partial = run_study(spec, checkpoint_path=ck, stop_after_chunks=1)
    assert partial["completed"] is False
    resumed = run_study(spec, checkpoint_path=ck)
    assert resumed["resumed_from_chunk"] == 1
    assert strip_timing(resumed) == strip_timing(run_study(spec))


def test_mismatched_agent_spec_restarts_clean(tmp_path):
    ck = str(tmp_path / "study.json")
    run_study(StudySpec(agents=_AGENTS, **_SPEC), checkpoint_path=ck,
              stop_after_chunks=1)
    other = StudySpec(agents=replace(_AGENTS, ev=13), **_SPEC)
    s = run_study(other, checkpoint_path=ck)
    assert s["resumed_from_chunk"] == 0 and s["completed"]


_CHILD = """
import os, sys
from freedm_tpu.scenarios.agents import AgentSpec
from freedm_tpu.scenarios.engine import StudySpec, run_study
spec = StudySpec(case="case14", scenarios=4, steps=48, dt_minutes=15.0,
                 chunk_steps=4, seed=7,
                 agents=AgentSpec(ev=12, thermostat=10, inverter=8, dr=6))
run_study(spec, checkpoint_path=sys.argv[1])
"""


def test_resume_after_sigkill_mid_study_is_exact(tmp_path):
    """A real SIGKILL (no cleanup, no atexit) mid-study: the chunk
    checkpoint on disk must resume to the exact uninterrupted summary
    in THIS process — cross-process bit determinism."""
    ck = str(tmp_path / "study.json")
    # Match conftest's config: the child must write its checkpoint at
    # the same precision this process resumes at.
    env = dict(os.environ, JAX_PLATFORMS="cpu", JAX_ENABLE_X64="1")
    child = subprocess.Popen([sys.executable, "-c", _CHILD, ck], env=env,
                             stdout=subprocess.DEVNULL,
                             stderr=subprocess.DEVNULL)
    try:
        deadline = time.monotonic() + 240.0
        while time.monotonic() < deadline:
            if os.path.exists(ck) or child.poll() is not None:
                break
            time.sleep(0.005)
        assert os.path.exists(ck), "child never wrote a chunk checkpoint"
        if child.poll() is None:
            child.send_signal(signal.SIGKILL)
        child.wait(timeout=30)
    finally:
        if child.poll() is None:
            child.kill()
    spec = StudySpec(case="case14", scenarios=4, steps=48, dt_minutes=15.0,
                     chunk_steps=4, seed=7, agents=_AGENTS)
    resumed = run_study(spec, checkpoint_path=ck)
    assert resumed["resumed_from_chunk"] >= 1
    assert resumed["completed"]
    assert strip_timing(resumed) == strip_timing(run_study(spec))


# ---------------------------------------------------------------------------
# mesh: sharded == unsharded, checkpoints placement-free
# ---------------------------------------------------------------------------


@needs_mesh
def test_mesh_agent_summary_byte_identical():
    # Byte identity is the f32 contract (test_mesh.py's convention);
    # local batch 2 (see module docstring).
    spec = dict(_SPEC, scenarios=2 * D2)
    with enable_x64(False):
        sharded = run_study(StudySpec(agents=_AGENTS, mesh_devices=D2,
                                      **spec))
        unsharded = run_study(StudySpec(agents=_AGENTS, **spec))
        assert sharded["mesh_devices"] == D2
        assert strip_timing(sharded) == strip_timing(unsharded)


@needs_mesh
def test_mesh_agent_summary_close_in_x64():
    # The x64 cousin: equal except GEMM-derived floats at 1e-12.
    spec = dict(_SPEC, scenarios=2 * D2)
    a = strip_timing(run_study(StudySpec(agents=_AGENTS, **spec)))
    b = strip_timing(run_study(StudySpec(agents=_AGENTS, mesh_devices=D2,
                                         **spec)))
    assert set(a) == set(b)
    for k in a:
        if isinstance(a[k], float):
            np.testing.assert_allclose(b[k], a[k], rtol=1e-12, err_msg=k)
        else:
            assert a[k] == b[k], k


@needs_mesh
def test_mesh_agent_checkpoint_is_placement_free(tmp_path):
    ck = str(tmp_path / "study.json")
    spec = dict(_SPEC, scenarios=2 * D2)
    with enable_x64(False):
        # Kill on a D2-device mesh, resume on a single device.
        run_study(StudySpec(agents=_AGENTS, mesh_devices=D2, **spec),
                  checkpoint_path=ck, stop_after_chunks=1)
        resumed = run_study(StudySpec(agents=_AGENTS, **spec),
                            checkpoint_path=ck)
        assert resumed["resumed_from_chunk"] == 1
        uninterrupted = run_study(StudySpec(agents=_AGENTS, **spec))
        assert strip_timing(resumed) == strip_timing(uninterrupted)
