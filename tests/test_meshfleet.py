"""Multi-chip operator path: the fleet round loop dispatching the
sharded superstep (VERDICT r4 weak #4 — cli.py gains a mesh mode and it
is the same module the driver dryrun validates)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from freedm_tpu.core.config import GlobalConfig, Timings
from freedm_tpu.devices.adapters.fake import FakeAdapter
from freedm_tpu.devices.manager import DeviceManager
from freedm_tpu.grid import cases
from freedm_tpu.parallel.mesh import make_mesh
from freedm_tpu.runtime.broker import Broker
from freedm_tpu.runtime.fleet import EgressModule, Fleet, NodeHandle
from freedm_tpu.runtime.meshfleet import MeshFleetModule

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fake_fleet(n_nodes=6, surplus_node=0, deficit_node=1):
    nodes = []
    for i in range(n_nodes):
        mgr = DeviceManager(capacity=4)
        gen = 25.0 if i == surplus_node else 5.0
        drain = 25.0 if i == deficit_node else 5.0
        fake = FakeAdapter(
            {
                (f"SST{i}", "gateway"): 0.0,
                (f"GEN{i}", "generation"): gen,
                (f"LOAD{i}", "drain"): drain,
            }
        )
        mgr.add_device(f"SST{i}", "Sst", fake)
        mgr.add_device(f"GEN{i}", "Drer", fake)
        mgr.add_device(f"LOAD{i}", "Load", fake)
        fake.reveal_devices()
        nodes.append(NodeHandle(f"node{i}:{50400 + i}", mgr))
    return Fleet(nodes, migration_step=1.0)


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh(8, axes=("nodes", "batch"))


def _run_rounds(fleet, mesh, n_rounds=3, **kw):
    mod = MeshFleetModule(fleet, cases.vvc_9bus(), mesh=mesh, **kw)
    broker = Broker()
    broker.register_module(mod, 1000)
    broker.register_module(EgressModule(fleet), 0)
    broker.run(n_rounds=n_rounds)
    return mod, broker


def test_round_loop_dispatches_superstep(mesh8):
    fleet = _fake_fleet()
    mod, broker = _run_rounds(fleet, mesh8)
    group = broker.shared["group"]
    lb = broker.shared["lb_round"]
    # All 6 alive nodes form one group (full reachability).
    assert int(group.n_groups) == 1
    # The surplus node's gateway moved power toward the deficit node.
    gw = np.asarray(lb.gateway)[: fleet.n_nodes]
    assert gw[0] > 0.0
    assert np.all(np.isfinite(gw))
    # VVC scenario lanes produced a finite mean loss.
    assert np.isfinite(broker.shared["vvc"].loss_after_kw)


def test_gateways_flow_back_through_adapters(mesh8):
    fleet = _fake_fleet()
    _run_rounds(fleet, mesh8)
    # The superstep's post-auction gateway actuated the fake transport
    # (FakeAdapter command becomes state immediately).
    sst0 = fleet.nodes[0].manager.get_state("SST0", "gateway")
    assert sst0 > 0.0


def test_dead_node_is_excluded(mesh8):
    fleet = _fake_fleet()
    fleet.set_alive(2, False)
    _, broker = _run_rounds(fleet, mesh8)
    group = broker.shared["group"]
    mask = np.asarray(group.group_mask)
    assert mask[2].sum() == 0  # dead node in no group
    assert int(group.n_groups) == 1  # the other five still form one


def test_node_padding_to_mesh_multiple(mesh8):
    # 6 nodes over a 4-way nodes axis pads to 8; padding rows must not
    # join groups or receive migrations.
    fleet = _fake_fleet(n_nodes=6)
    mod, broker = _run_rounds(fleet, mesh8)
    lb = broker.shared["lb_round"]
    gw = np.asarray(lb.gateway)
    assert gw.shape[0] == mod._padded(6)
    assert np.all(gw[6:] == 0.0)


def test_vvc_state_carried_across_rounds(mesh8):
    fleet = _fake_fleet()
    mod, broker = _run_rounds(fleet, mesh8, n_rounds=4)
    # The VVC gradient controller accumulated setpoints on device.
    q = np.asarray(mod._state.q_ctrl)
    assert np.abs(q).sum() > 0.0
    assert broker.shared["vvc"].improved


def test_invariant_gates_mesh_migrations(mesh8):
    import jax.numpy as jnp

    blocked = _fake_fleet()
    mod = MeshFleetModule(
        blocked, cases.vvc_9bus(), mesh=mesh8,
        invariant=lambda readings: jnp.asarray(0.0),
    )
    broker = Broker()
    broker.register_module(mod, 1000)
    broker.register_module(EgressModule(blocked), 0)
    broker.run(n_rounds=2)
    lb = broker.shared["lb_round"]
    assert int(lb.n_migrations) == 0
    assert np.all(np.asarray(lb.gateway) == 0.0)
    # Same fleet shape without the gate migrates (the gate, not the
    # rig, is what blocked it).
    open_fleet = _fake_fleet()
    _, broker2 = _run_rounds(open_fleet, mesh8)
    assert int(broker2.shared["lb_round"].n_migrations) > 0


def test_mesh_checkpoint_roundtrip(mesh8):
    from freedm_tpu.runtime import checkpoint as ckpt

    fleet = _fake_fleet()
    mod, broker = _run_rounds(fleet, mesh8, n_rounds=3)
    state = ckpt.collect_state(broker, fleet)
    assert state["mesh"]["q_ctrl"] is not None
    assert state["mesh"]["rounds"] == 3

    fleet2 = _fake_fleet()
    mod2 = MeshFleetModule(fleet2, cases.vvc_9bus(), mesh=mesh8)
    broker2 = Broker()
    broker2.register_module(mod2, 1000)
    broker2.register_module(EgressModule(fleet2), 0)
    ckpt.restore_state(state, broker2, fleet2)
    assert mod2.rounds == 3
    broker2.run(n_rounds=1)
    # The restored q_ctrl seeded the carried scenario state: after one
    # round it matches a 4-round run, not a 1-round run.
    q_resumed = np.asarray(mod2._state.q_ctrl)
    fleet3 = _fake_fleet()
    mod3, _ = _run_rounds(fleet3, mesh8, n_rounds=4)
    np.testing.assert_allclose(
        q_resumed, np.asarray(mod3._state.q_ctrl), atol=1e-5
    )


def test_cli_e2e_mesh_mode(tmp_path):
    # The CLI operator path over the 8-device virtual mesh, from config
    # files alone (VERDICT item: "a CLI e2e test running the fleet over
    # the 8-device virtual mesh").
    from freedm_tpu.devices.schema import DEFAULT_TYPES

    lines = ["<root>"]
    for t in DEFAULT_TYPES:
        lines.append(f"  <deviceType><id>{t.id}</id>")
        for s in t.states:
            lines.append(f"    <state>{s}</state>")
        for c in t.commands:
            lines.append(f"    <command>{c}</command>")
        lines.append("  </deviceType>")
    lines.append("</root>")
    (tmp_path / "device.xml").write_text("\n".join(lines))

    # Three nodes of fake-transport devices, seeded with an LB imbalance
    # (reference adapter.xml entry format, value= seeds the fake state).
    adapter = ["<root>"]
    for uuid, seeds in {
        "node0:50820": [("SST1", "Sst", "gateway", 0),
                        ("DRER_A", "Drer", "generation", 30),
                        ("LOAD_A", "Load", "drain", 10)],
        "node1:50821": [("SST2", "Sst", "gateway", 0),
                        ("LOAD_B", "Load", "drain", 30)],
        "node2:50822": [("SST3", "Sst", "gateway", 0),
                        ("DRER_C", "Drer", "generation", 10),
                        ("LOAD_C", "Load", "drain", 10)],
    }.items():
        owner = "" if uuid.startswith("node0") else f' owner="{uuid}"'
        adapter.append(f'  <adapter name="fake-{uuid.split(":")[0]}" type="fake"{owner}>')
        adapter.append("    <state>")
        for i, (dev, typ, sig, val) in enumerate(seeds):
            adapter.append(
                f'      <entry index="{i + 1}" value="{val}"><type>{typ}</type>'
                f"<device>{dev}</device><signal>{sig}</signal></entry>"
            )
        adapter.append("    </state>")
        adapter.append("  </adapter>")
    adapter.append("</root>")
    (tmp_path / "adapter.xml").write_text("\n".join(adapter))

    (tmp_path / "freedm.cfg").write_text(
        "hostname = node0\nport = 50820\n"
        "add-host = node1:50821\nadd-host = node2:50822\n"
        "mesh-devices = 8\nmesh-scenarios = 8\nmigration-step = 1\n"
        "vvc-case = vvc_9bus\n"
        f"device-config = {tmp_path}/device.xml\n"
        f"adapter-config = {tmp_path}/adapter.xml\n"
    )
    env = dict(
        os.environ,
        PYTHONPATH=REPO,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
    )
    out = subprocess.run(
        [sys.executable, "-m", "freedm_tpu", "-c", str(tmp_path / "freedm.cfg"),
         "--rounds", "4", "--summary-every", "1"],
        capture_output=True, env=env, text=True, timeout=600,
    )
    assert out.returncode == 0, out.stderr[-3000:]
    lines = [json.loads(l) for l in out.stdout.splitlines() if l.startswith("{")]
    assert len(lines) == 4
    assert lines[-1]["n_groups"] == 1
    assert "vvc_loss_kw" in lines[-1]
    assert sum(l.get("migrations", 0) for l in lines) > 0


def test_mesh_and_federate_are_mutually_exclusive():
    from freedm_tpu.cli import build_runtime

    cfg = GlobalConfig(mesh_devices=8, federate=True, add_host=["h:1"])
    with pytest.raises(ValueError, match="different deployment"):
        build_runtime(cfg, Timings())
