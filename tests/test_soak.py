"""CI wrapper around the federation soak rig (freedm_tpu/tools/soak.py).

The full artifact run is 5 slices, 20% loss, VVC on every slice
(``python -m freedm_tpu.tools.soak``; the committed SOAK_r05.json is
one such run).  CI runs a reduced-but-real version: two federated
subprocesses + plantserver over real sockets, scripted member AND
leader kills with rejoins — every check machinery path, bounded time.
"""

import os

from freedm_tpu.tools.soak import run_soak


def test_soak_two_slices_quick(tmp_path):
    artifact = run_soak(
        n_slices=2,
        duration_s=20.0,
        loss_pct=0,
        workdir=str(tmp_path),
        out=str(tmp_path / "soak.json"),
        vvc=False,
    )
    failed = [c for c in artifact["checks"] if not c["ok"]]
    assert artifact["pass"], failed
    assert os.path.exists(tmp_path / "soak.json")
