"""Roofline observatory tests (``freedm_tpu.core.roofline``).

Covers: the static join against a hand-written gridprobe inventory
(achieved FLOP/s, MFU, intensity, bound class, per-program roof and
headroom), dispatch-only attribution (async sites credit nothing), the
disabled-by-default no-op path (the acceptance bar: one attribute
check, no recorded state), ``traced_solver`` steady-state attribution
including the under-a-jax-trace (vmap) guard, the ``/roofline`` route
schema, the checked-in roofline inventory's consistency, and the CI
drift gate (``diff_roofline_inventory`` plus ``bench.py``'s exit-1
path on a mutated inventory).
"""

import importlib.util
import json
import pathlib
import urllib.request

import pytest

from freedm_tpu.core import metrics as M
from freedm_tpu.core import roofline, tracing

REPO = pathlib.Path(__file__).resolve().parent.parent
CHECKED_IN = REPO / "freedm_tpu" / "tools" / "roofline_inventory.json"


def _toy_inventory(tmp_path, name="toy/prog", flops=2e9, by=1e9):
    """A minimal gridprobe-shaped inventory with one known program:
    intensity flops/by (= 2.0 by default, memory-bound on the CPU
    row's balance of 2.5)."""
    p = tmp_path / "ir_inventory.json"
    p.write_text(json.dumps({
        "programs": {name: {"flops": flops, "bytes_accessed": by}},
    }))
    return p


@pytest.fixture
def rl(tmp_path):
    """An enabled observatory pinned to the CPU peak row and a toy
    inventory; hard-reset afterwards so the rest of the suite runs on
    the disabled no-op path."""
    roofline.ROOFLINE.configure(
        enabled=True,
        inventory_path=str(_toy_inventory(tmp_path)),
        peak_flops=5e10,
        peak_bytes=2e10,
    )
    yield roofline.ROOFLINE
    roofline.ROOFLINE.reset()


# ---------------------------------------------------------------------------
# static join + attribution arithmetic
# ---------------------------------------------------------------------------


def test_static_join_attributes_measured_wall_to_model_costs(rl):
    # Two blocked dispatches of 0.5 s each at scale 1.0: 4e9 model
    # FLOPs over 1.0 s of device wall.
    rl.record_dispatch("toy/prog", device_s=0.5)
    rl.record_dispatch("toy/prog", device_s=0.5)
    row = rl.report()["programs"]["toy/prog"]
    assert row["dispatches"] == 2
    assert row["blocked_dispatches"] == 2
    assert row["device_s"] == pytest.approx(1.0)
    assert row["intensity_flops_per_byte"] == pytest.approx(2.0)
    assert row["bound"] == "memory"  # 2.0 < balance 5e10/2e10 = 2.5
    assert row["achieved_flops_per_s"] == pytest.approx(4e9)
    assert row["achieved_bytes_per_s"] == pytest.approx(2e9)
    assert row["mfu_pct"] == pytest.approx(100 * 4e9 / 5e10)  # 8 %
    # The program's own roof is its bandwidth ceiling:
    # intensity * peak_bytes = 2.0 * 2e10 = 4e10 < peak_flops.
    assert row["roof_flops_per_s"] == pytest.approx(4e10)
    assert row["roof_pct"] == pytest.approx(10.0)
    assert row["headroom_s"] == pytest.approx(0.9)
    # The headroom ranking surfaces it as the top target.
    targets = rl.report(top_n=3)["targets"]
    assert targets and targets[0]["program"] == "toy/prog"


def test_scale_multiplies_model_costs(rl):
    # A half-shape dispatch credits half the registered trace's cost.
    rl.record_dispatch("toy/prog", device_s=0.5, scale=0.5)
    row = rl.report()["programs"]["toy/prog"]
    assert row["achieved_flops_per_s"] == pytest.approx(2e9)


def test_dispatch_only_counts_but_credits_nothing(rl):
    # device_s=None is the async-dispatch site contract: counted,
    # never credited — no fabricated throughput.
    rl.record_dispatch("toy/prog")
    row = rl.report()["programs"]["toy/prog"]
    assert row["dispatches"] == 1
    assert row["blocked_dispatches"] == 0
    assert row["achieved_flops_per_s"] is None
    assert row["mfu_pct"] is None
    # Model columns are served even without any wall credit.
    assert row["bound"] == "memory"


def test_unknown_program_still_counts_dispatches(rl):
    rl.record_dispatch("not/registered", device_s=0.1)
    row = rl.report()["programs"]["not/registered"]
    assert row["dispatches"] == 1
    assert row["model_flops"] is None
    assert row["bound"] == "unknown"
    assert row["achieved_flops_per_s"] is None


# ---------------------------------------------------------------------------
# disabled-by-default tripwire
# ---------------------------------------------------------------------------


def test_disabled_mode_records_nothing():
    # The acceptance bar: when off, instrumented sites pay one
    # attribute check and record_dispatch is a no-op.
    assert roofline.ROOFLINE.enabled is False
    before = roofline.ROOFLINE._programs.copy()
    roofline.ROOFLINE.record_dispatch("toy/prog", device_s=1.0)
    assert roofline.ROOFLINE._programs == before
    assert roofline.ROOFLINE.snapshot()["enabled"] is False


# ---------------------------------------------------------------------------
# traced_solver attribution (+ the vmap/trace guard)
# ---------------------------------------------------------------------------


def test_traced_solver_steady_state_dispatches_are_attributed(tmp_path):
    roofline.ROOFLINE.configure(
        enabled=True,
        inventory_path=str(_toy_inventory(tmp_path, "pf/newton/dense")),
        peak_flops=5e10, peak_bytes=2e10,
    )
    try:
        wrapped = tracing.traced_solver(
            "newton", lambda x: x * 2.0, tags={"pf_backend": "dense"})
        wrapped(1.0)  # first call = compile, never attributed
        wrapped(1.0)
        wrapped(1.0)
        row = roofline.ROOFLINE.report()["programs"]["pf/newton/dense"]
        assert row["dispatches"] == 2
        # Steady-state solver dispatches are async: dispatch-only.
        assert row["blocked_dispatches"] == 0
    finally:
        roofline.ROOFLINE.reset()


def test_traced_solver_under_vmap_records_nothing(tmp_path):
    # A solver re-entered inside a jax transformation trace (vmap here)
    # is one device program, not N dispatches — the trace guard must
    # keep every traced call out of the account.
    import jax
    import jax.numpy as jnp

    roofline.ROOFLINE.configure(
        enabled=True,
        inventory_path=str(_toy_inventory(tmp_path, "pf/newton/dense")),
        peak_flops=5e10, peak_bytes=2e10,
    )
    try:
        wrapped = tracing.traced_solver(
            "newton", lambda x: x * 2.0, tags={"pf_backend": "dense"})
        vf = jax.vmap(wrapped)
        vf(jnp.arange(4.0))
        vf(jnp.arange(4.0))  # steady state, still inside the trace
        assert "pf/newton/dense" not in roofline.ROOFLINE._programs
    finally:
        roofline.ROOFLINE.reset()


def test_solver_program_vocabulary():
    assert roofline.solver_program("newton", "dense") == "pf/newton/dense"
    assert roofline.solver_program("newton", "sparse") == "pf/newton/sparse"
    assert roofline.solver_program(
        "newton", "sparse", "mixed") == "pf/newton/sparse/mixed"
    assert roofline.solver_program("krylov", "matrix_free") == "pf/krylov"
    assert roofline.solver_program("nosuch") is None


# ---------------------------------------------------------------------------
# /roofline route
# ---------------------------------------------------------------------------


def test_roofline_route_serves_full_report(tmp_path):
    roofline.ROOFLINE.configure(
        enabled=True, peak_flops=5e10, peak_bytes=2e10)
    roofline.ROOFLINE.record_dispatch("pf/newton/dense", device_s=0.25)
    server = M.MetricsServer(port=0).start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/roofline", timeout=5
        ) as r:
            served = json.loads(r.read())
        # A malformed capture request is rejected up front (400), not
        # handed to the profiler.
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/profile/capture?ms=0",
            method="POST")
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=5)
        assert exc.value.code == 400
    finally:
        server.stop()
        roofline.ROOFLINE.reset()
    assert served["enabled"] is True
    assert set(served) == {"enabled", "peak", "programs", "targets"}
    assert served["peak"]["flops_per_s"] == pytest.approx(5e10)
    # Every statically registered program appears, dispatched or not.
    assert len(served["programs"]) >= 21
    row = served["programs"]["pf/newton/dense"]
    assert row["dispatches"] == 1
    assert row["mfu_pct"] is not None
    for col in ("intensity_flops_per_byte", "bound", "headroom_s"):
        assert col in row


# ---------------------------------------------------------------------------
# the checked-in inventory + drift gate
# ---------------------------------------------------------------------------


def test_checked_in_roofline_inventory_matches_static_model():
    # The gated columns are pure functions of the checked-in gridprobe
    # inventory and the CPU peak row — a fresh report (no measurement
    # needed) must be diff-clean against the committed file.
    recorded = json.loads(CHECKED_IN.read_text())
    roofline.ROOFLINE.configure(enabled=True)
    try:
        inv = roofline.build_roofline_inventory(
            roofline.ROOFLINE.report())
    finally:
        roofline.ROOFLINE.reset()
    assert roofline.diff_roofline_inventory(inv, recorded, tol=0.5) == []
    assert len(recorded["programs"]) >= 21


def test_diff_rejects_model_drift(rl):
    rl.record_dispatch("toy/prog", device_s=0.5)
    inv = roofline.build_roofline_inventory(rl.report())
    assert roofline.diff_roofline_inventory(inv, inv, tol=0.5) == []
    # Measured columns never gate: a noisy rerun stays clean.
    noisy = json.loads(json.dumps(inv))
    noisy["programs"]["toy/prog"]["measured"]["mfu_pct"] = 99.0
    assert roofline.diff_roofline_inventory(noisy, inv, tol=0.5) == []
    # Model drift fails: flops beyond tolerance and a bound flip.
    drifted = json.loads(json.dumps(inv))
    drifted["programs"]["toy/prog"]["flops"] *= 4
    drifted["programs"]["toy/prog"]["bound"] = "compute"
    findings = roofline.diff_roofline_inventory(drifted, inv, tol=0.5)
    assert len(findings) == 2
    assert any("bound class" in f for f in findings)
    assert any("flops drifted" in f for f in findings)
    # Program set changes are findings in both directions.
    gone = json.loads(json.dumps(inv))
    del gone["programs"]["toy/prog"]
    assert any("no longer measured" in f
               for f in roofline.diff_roofline_inventory(gone, inv, 0.5))
    assert any("new program" in f
               for f in roofline.diff_roofline_inventory(inv, gone, 0.5))
    # A backend mismatch short-circuits: nothing else is comparable.
    other = json.loads(json.dumps(inv))
    other["backend"] = "tpu_v5e"
    findings = roofline.diff_roofline_inventory(inv, other, 0.5)
    assert len(findings) == 1 and "backend drifted" in findings[0]


def test_bench_roofline_exits_1_on_drifted_inventory(tmp_path, monkeypatch):
    # The CI contract end to end: bench --sections roofline against a
    # mutated inventory must exit 1.  The registry measurement is
    # stubbed out — the gate runs on the static join alone.
    spec = importlib.util.spec_from_file_location(
        "bench", str(REPO / "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    monkeypatch.setattr(
        roofline.ROOFLINE, "measure_registry",
        lambda repeats=3, programs=None: {"measured": [], "errors": {}})
    recorded = json.loads(CHECKED_IN.read_text())
    recorded["programs"]["pf/newton/dense"]["flops"] *= 4
    mutated = tmp_path / "roofline_inventory.json"
    mutated.write_text(json.dumps(recorded))
    try:
        with pytest.raises(SystemExit) as exc:
            bench.bench_roofline(str(mutated), tol=0.5, repeats=1)
        assert exc.value.code == 1
        # And the clean path: the same run against the committed file
        # is diff-clean and reports it was not rewritten.
        out = bench.bench_roofline(str(CHECKED_IN), tol=0.5, repeats=1)
        assert out["roofline_inventory_written"] is False
    finally:
        roofline.ROOFLINE.reset()
