"""Clock synchronizer tests (VERDICT r3 item 3).

Reference: ``CClockSynchronizer`` (``Broker/src/CClockSynchronizer.cpp:165-369``)
— pairwise challenge/response, ≤200-sample linear regression, weighted
offset/skew average feeding the broker's phase alignment.  Two realtime
brokers with injected host-clock offsets must phase-lock within
ALIGNMENT_DURATION.
"""

import threading
import time

import pytest

from freedm_tpu.core.config import ALIGNMENT_DURATION_MS
from freedm_tpu.dcn.endpoint import UdpEndpoint
from freedm_tpu.runtime import Broker, DgiModule
from freedm_tpu.runtime.clocksync import ClockSynchronizer
from freedm_tpu.runtime.messages import ModuleMessage

from test_federation import free_udp_ports


def wire_pair(offset_a, offset_b):
    """Two synchronizers on offset clocks, wired back-to-back (no UDP)."""
    clocks = {
        "a": lambda: time.time() + offset_a,
        "b": lambda: time.time() + offset_b,
    }
    clks = {}

    def send(src):
        def _send(uuid, msg):
            clks[uuid].handle_message(msg)

        return _send

    clks["a"] = ClockSynchronizer("a", ["b"], send("a"), clock=clocks["a"])
    clks["b"] = ClockSynchronizer("b", ["a"], send("b"), clock=clocks["b"])
    return clks["a"], clks["b"]


def test_pairwise_exchange_agrees_virtual_clocks():
    """±300 ms host offsets: after a few exchange rounds both virtual
    clocks read the same time (each side meets halfway)."""
    a, b = wire_pair(-0.3, +0.3)
    for _ in range(4):
        a.exchange()
        b.exchange()
        time.sleep(0.02)  # x-spread for the regression
    # Offsets each ≈ half the 600 ms gap, in opposite directions.
    assert a.offset_s == pytest.approx(0.3, abs=0.02)
    assert b.offset_s == pytest.approx(-0.3, abs=0.02)
    assert abs(a.virtual_now() - b.virtual_now()) < 0.02


def test_regression_handles_many_samples_and_cap():
    a, b = wire_pair(-0.1, +0.1)
    for _ in range(250):  # beyond MAX_REGRESSION_ENTRIES
        a.exchange()
    assert len(a._responses["b"]) <= 400
    assert a.offset_s == pytest.approx(0.1, abs=0.02)


def test_transitive_table_entries_adopted():
    """A peer's offset table seeds third-party entries at reduced trust
    (HandleExchangeResponse table loop)."""
    a, b = wire_pair(0.0, +0.2)
    # b knows a third process "c" at +0.5 relative to itself.
    from freedm_tpu.runtime.clocksync import _Entry

    b._table["c"] = _Entry(0.5, 0.0, 1.0)
    for _ in range(3):
        a.exchange()
        time.sleep(0.01)
    assert "c" in a._table
    # a's view of c = (b − a) + (c − b) ≈ 0.2 + 0.5.
    assert a._table["c"].offset == pytest.approx(0.7, abs=0.03)
    assert a._table["c"].weight == pytest.approx(0.9)


class PhaseRecorder(DgiModule):
    name = "rec"

    def __init__(self):
        self.starts = []

    def run_phase(self, ctx):
        self.starts.append(time.time())


def test_realtime_brokers_phase_lock(tmp_path):
    """Two realtime brokers on hosts whose clocks disagree by 400 ms
    phase-lock: once synchronized, their round boundaries land within
    ALIGNMENT_DURATION of each other (ChangePhase parity)."""
    pa, pb = free_udp_ports(2)
    uuid_a, uuid_b = f"127.0.0.1:{pa}", f"127.0.0.1:{pb}"
    offsets = {uuid_a: -0.2, uuid_b: +0.2}
    brokers, recs, eps = {}, {}, {}
    for uuid, port, peer in ((uuid_a, pa, uuid_b), (uuid_b, pb, uuid_a)):
        clock = (lambda off: lambda: time.time() + off)(offsets[uuid])
        broker = Broker(clock=clock)
        rec = PhaseRecorder()
        broker.register_module(rec, 1000)  # one 1 s phase = the round
        ep = UdpEndpoint(uuid, bind=("127.0.0.1", port), sink=broker.deliver)
        ep.connect(peer, ("127.0.0.1", int(peer.rsplit(":", 1)[1])))
        clk = ClockSynchronizer(uuid, [peer], ep.send, query_interval_s=0.4)
        broker.attach_clock_sync(clk)
        ep.start()
        brokers[uuid], recs[uuid], eps[uuid] = broker, rec, ep
    threads = [
        threading.Thread(target=lambda b=b: b.run(n_rounds=8, realtime=True))
        for b in brokers.values()
    ]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        # Without sync the 400 ms clock gap would keep the 1 s rounds
        # 400 ms apart; with sync the final boundaries agree.
        sa, sb = recs[uuid_a].starts, recs[uuid_b].starts
        assert len(sa) == len(sb) == 8
        last_diff = abs(sa[-1] - sb[-1]) % 1.0
        last_diff = min(last_diff, 1.0 - last_diff)
        assert last_diff <= ALIGNMENT_DURATION_MS / 1000.0, (sa, sb)
        # And both brokers actually measured/applied a skew.
        for uuid, broker in brokers.items():
            assert broker.clock_skew_s == pytest.approx(-offsets[uuid], abs=0.05)
    finally:
        for ep in eps.values():
            ep.stop()


def test_immediate_dispatch_for_clk_messages():
    """clk responses must not wait for a phase: the dispatcher delivers
    them immediately (unscheduled module, CDispatcher.cpp:68-103)."""
    broker = Broker()
    got = []
    clk = ClockSynchronizer("x", [], lambda u, m: got.append((u, m)))
    broker.attach_clock_sync(clk)
    broker.deliver(
        ModuleMessage("clk", "exchange", {"query": 7}, source="y").stamped()
    )
    # Handled synchronously — no run_round happened.
    assert got and got[0][0] == "y"
    assert got[0][1].payload["response"] == 7


def test_large_skew_survives_dispatcher_expiry():
    """Hosts 10 s apart must still synchronize: clk messages carry no
    wall-clock TTL, or the dispatcher's expiry check (made against the
    receiver's UNsynchronized clock) would drop every exchange — the
    exact condition the synchronizer exists to correct."""
    brokers, clks = {}, {}

    def send(src):
        def _send(uuid, msg):
            brokers[uuid].deliver(msg)  # dispatch path incl. expiry check

        return _send

    offs = {"a": -5.0, "b": +5.0}
    for u, peer in (("a", "b"), ("b", "a")):
        clock = (lambda o: lambda: time.time() + o)(offs[u])
        brokers[u] = Broker(clock=clock)
        clks[u] = ClockSynchronizer(u, [peer], send(u), clock=clock)
        brokers[u].attach_clock_sync(clks[u])
    for _ in range(4):
        clks["a"].exchange()
        clks["b"].exchange()
        time.sleep(0.02)
    assert clks["a"].offset_s == pytest.approx(5.0, abs=0.05)
    assert clks["b"].offset_s == pytest.approx(-5.0, abs=0.05)
