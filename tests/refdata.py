"""Reference-data resolution for tests.

Some tests exercise loaders against the reference checkout's own data
files (``/root/reference/...``).  That checkout is not part of this
repo, so each such file has a converted fixture committed under
``tests/data/`` — the fixture wins when both exist (deterministic CI),
the reference checkout is the fallback, and a clean skip (not an error)
is the outcome when neither is present.
"""

import os

import pytest

DATA_DIR = os.path.join(os.path.dirname(__file__), "data")


def resolve(fixture_name: str, reference_path: str) -> str:
    """Fixture-first path resolution with a skip-with-reason fallback."""
    fixture = os.path.join(DATA_DIR, fixture_name)
    for path in (fixture, reference_path):
        if os.path.exists(path):
            return path
    pytest.skip(
        f"no {fixture_name}: neither the committed fixture ({fixture}) "
        f"nor the reference checkout ({reference_path}) exists"
    )
