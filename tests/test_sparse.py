"""Sparsity-aware power-flow path (pf/sparse.py, pf/dc.py): dense-vs-
sparse equivalence, pattern reuse, the DC screen's oracles, and the
mesh-sharded forms.

Tolerance semantics (docs/solvers.md): both backends iterate the SAME
masked power-mismatch test to the same ``tol``, so convergence flags
must agree exactly; the converged *solutions* agree to solver-tolerance
level (inexact Newton vs direct LU), pinned here at 1e-6 pu in the
float64 test dtype — measured agreement is ~1e-15, so a failure at
1e-6 means the math broke, not the tolerance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from freedm_tpu.grid.bus import PQ, SLACK, BusSystem
from freedm_tpu.grid.cases import synthetic_mesh
from freedm_tpu.pf import dc as dc_mod
from freedm_tpu.pf import sparse as sparse_mod
from freedm_tpu.pf.fdlf import decoupled_parts
from freedm_tpu.pf.n1 import make_n1_screen
from freedm_tpu.pf.newton import make_newton_solver
from freedm_tpu.pf.sparse import (
    SPARSE_AUTO_MIN_BUSES,
    jacobian_pattern,
    make_sparse_newton_solver,
    resolve_backend,
)

D = jax.local_device_count()
D2 = max(d for d in (1, 2, 4) if d <= D and D % d == 0)
needs_mesh = pytest.mark.skipif(D2 < 2, reason="single-device host")

ATOL_V = 1e-6  # pu; see module docstring


@pytest.fixture(scope="module")
def mesh118():
    return synthetic_mesh(118, seed=1, load_mw=10.0, chord_frac=1.0)


@pytest.fixture(scope="module")
def solvers118(mesh118):
    dense, dense_fixed = make_newton_solver(mesh118, max_iter=10)
    sp, sp_fixed = make_sparse_newton_solver(
        mesh118, max_iter=12, inner_iters=16
    )
    return dense, dense_fixed, sp, sp_fixed


# ---------------------------------------------------------------------------
# backend resolution
# ---------------------------------------------------------------------------


def test_resolve_backend():
    assert resolve_backend("dense", 10_000) == "dense"
    assert resolve_backend("sparse", 14) == "sparse"
    assert resolve_backend("auto", SPARSE_AUTO_MIN_BUSES - 1) == "dense"
    assert resolve_backend("auto", SPARSE_AUTO_MIN_BUSES) == "sparse"
    with pytest.raises(ValueError, match="unknown pf backend"):
        resolve_backend("bogus", 100)


def test_make_newton_solver_dispatches_backend(mesh118):
    # backend="sparse" through the dense entry point returns the sparse
    # solvers — same signature, same NewtonResult, solutions matching.
    solve, _ = make_newton_solver(mesh118, max_iter=12, backend="sparse")
    dense, _ = make_newton_solver(mesh118, max_iter=10)
    r_s, r_d = solve(), dense()
    assert bool(r_s.converged) and bool(r_d.converged)
    np.testing.assert_allclose(
        np.asarray(r_s.v), np.asarray(r_d.v), atol=ATOL_V
    )


# ---------------------------------------------------------------------------
# sparse-vs-dense equivalence: newton / N-1 / batched lanes
# ---------------------------------------------------------------------------


def test_sparse_matches_dense_base_case(solvers118):
    dense, _, sp, _ = solvers118
    r_d, r_s = dense(), sp()
    assert bool(r_d.converged) == bool(r_s.converged) is True
    assert float(r_s.mismatch) < 1e-8
    np.testing.assert_allclose(
        np.asarray(r_s.v), np.asarray(r_d.v), atol=ATOL_V
    )
    np.testing.assert_allclose(
        np.asarray(r_s.theta), np.asarray(r_d.theta), atol=ATOL_V
    )
    # Realized injections (the result's P/Q) agree too — the sparse
    # assembly IS the Ybus power evaluation, written edge-wise.
    np.testing.assert_allclose(
        np.asarray(r_s.p), np.asarray(r_d.p), atol=1e-6
    )


def test_sparse_matches_dense_outage_lane(mesh118, solvers118):
    dense, _, sp, _ = solvers118
    status = np.ones(mesh118.n_branch)
    status[mesh118.n_bus + 5] = 0.0  # a chord: never islands the ring
    st = jnp.asarray(status)
    r_d, r_s = dense(status=st), sp(status=st)
    assert bool(r_d.converged) == bool(r_s.converged) is True
    np.testing.assert_allclose(
        np.asarray(r_s.v), np.asarray(r_d.v), atol=ATOL_V
    )


def test_sparse_matches_dense_vmapped_batch(mesh118, solvers118):
    _, dense_fixed, _, sp_fixed = solvers118
    rng = np.random.default_rng(0)
    scale = rng.uniform(0.9, 1.1, (8, 1))
    p = jnp.asarray(scale * mesh118.p_inj[None])
    q = jnp.asarray(scale * mesh118.q_inj[None])
    r_d = jax.jit(jax.vmap(
        lambda pi, qi: dense_fixed(p_inj=pi, q_inj=qi)
    ))(p, q)
    r_s = jax.jit(jax.vmap(
        lambda pi, qi: sp_fixed(p_inj=pi, q_inj=qi)
    ))(p, q)
    assert bool(jnp.all(r_d.converged)) and bool(jnp.all(r_s.converged))
    np.testing.assert_allclose(
        np.asarray(r_s.v), np.asarray(r_d.v), atol=ATOL_V
    )


def test_sparse_n1_screen_matches_smw(mesh118):
    smw = make_n1_screen(mesh118, max_iter=24)  # backend="dense"
    sp = make_n1_screen(mesh118, max_iter=24, backend="sparse")
    ks = jnp.arange(118, 130)  # chord outages
    r1, r2 = smw(ks), sp(ks)
    assert bool(np.all(np.asarray(r1.converged)))
    assert bool(np.all(np.asarray(r2.converged)))
    np.testing.assert_allclose(
        np.asarray(r2.v), np.asarray(r1.v), atol=ATOL_V
    )


def test_sparse_warm_start_seeds_iteration(mesh118, solvers118):
    # v0/theta0 are traced on the sparse path too: restarting from the
    # solution converges immediately (the QSTS warm-start contract).
    _, _, sp, _ = solvers118
    base = sp()
    again = sp(v0=base.v, theta0=base.theta)
    assert int(again.iterations) <= 1
    assert bool(again.converged)


# ---------------------------------------------------------------------------
# pattern reuse: ONE symbolic build per (case, topology)
# ---------------------------------------------------------------------------


def test_pattern_built_once_per_topology():
    sys_a = synthetic_mesh(97, seed=101, load_mw=5.0, chord_frac=0.5)
    before = sparse_mod.pattern_builds
    s1, _ = make_sparse_newton_solver(sys_a, max_iter=8)
    s2, _ = make_sparse_newton_solver(sys_a, max_iter=12)  # same topology
    screen = make_n1_screen(sys_a, max_iter=8, backend="sparse")
    assert sparse_mod.pattern_builds == before + 1
    # A different topology is a new pattern...
    sys_b = synthetic_mesh(97, seed=102, load_mw=5.0, chord_frac=0.5)
    make_sparse_newton_solver(sys_b, max_iter=8)
    assert sparse_mod.pattern_builds == before + 2
    # ...and solving (any number of times, any lane count) builds none.
    r = s1()
    jax.vmap(lambda k: s2(status=jnp.ones(sys_a.n_branch).at[k].set(0.0)))(
        jnp.asarray([sys_a.n_bus + 1, sys_a.n_bus + 2])
    )
    screen(jnp.asarray([sys_a.n_bus + 1]))
    assert sparse_mod.pattern_builds == before + 2
    assert bool(r.converged)


def test_pattern_nnz_bookkeeping(mesh118):
    pat = jacobian_pattern(mesh118)
    # 4 polar blocks, each with n diagonal + 2 entries per unique
    # off-diagonal pair.
    pairs = {
        (min(f, t), max(f, t))
        for f, t in zip(mesh118.from_bus, mesh118.to_bus) if f != t
    }
    assert pat.nnz == 4 * (mesh118.n_bus + 2 * len(pairs))
    assert pat.blocks == 4
    # >99% sparse at the 118-bus scale already.
    assert pat.nnz < 0.1 * (2 * mesh118.n_bus) ** 2


def test_pattern_gauge_recorded_when_profiling():
    from freedm_tpu.core import profiling

    profiling.PROFILER.configure(enabled=True)
    try:
        sys_c = synthetic_mesh(131, seed=7, load_mw=5.0, chord_frac=0.4)
        make_sparse_newton_solver(sys_c, max_iter=6)
        snap = profiling.PROFILER.snapshot()
        # Label = bus count + topology digest, so two distinct 131-bus
        # cases publish two gauges instead of overwriting one.
        key = next(k for k in snap["pf_patterns"] if k.startswith("131bus-"))
        ent = snap["pf_patterns"][key]
        assert ent["blocks"] == 4 and ent["nnz"] > 0
        sys_d = synthetic_mesh(131, seed=8, load_mw=5.0, chord_frac=0.4)
        make_sparse_newton_solver(sys_d, max_iter=6)
        snap2 = profiling.PROFILER.snapshot()
        assert sum(
            k.startswith("131bus-") for k in snap2["pf_patterns"]
        ) == 2
        host = snap["host"]
        assert host["sparse.pattern_build"]["count"] >= 1
        assert host["sparse.precond_build"]["count"] >= 1
    finally:
        profiling.PROFILER.reset()


# ---------------------------------------------------------------------------
# DC loadflow screen (pf/dc.py)
# ---------------------------------------------------------------------------


def _dc_oracle(sys_, rhs_mask_free=True, outage=None):
    parts = decoupled_parts(sys_, jnp.float64)
    b = np.asarray(parts.b_prime(None), np.float64)
    tf = np.asarray(parts.th_free)
    if outage is not None:
        w = 1.0 / float(sys_.x[outage])
        a = np.zeros(sys_.n_bus)
        fb, tb = int(sys_.from_bus[outage]), int(sys_.to_bus[outage])
        a[fb] += tf[fb]
        a[tb] -= tf[tb]
        b = b - w * np.outer(a, a)
    rhs = np.where(tf > 0, sys_.p_inj, 0.0)
    return np.linalg.solve(b, rhs)


def test_dc_solve_matches_linear_oracle(mesh118):
    dcs = dc_mod.make_dc_solver(mesh118)
    r = dcs.solve()
    np.testing.assert_allclose(
        np.asarray(r.theta), _dc_oracle(mesh118), atol=1e-10
    )
    # Injection lanes: one multi-RHS solve, row i == solo solve of row i.
    lanes = np.stack([mesh118.p_inj * s for s in (0.8, 1.0, 1.2)])
    rl = dcs.solve(jnp.asarray(lanes))
    assert rl.theta.shape == (3, mesh118.n_bus)
    np.testing.assert_allclose(
        np.asarray(rl.theta[1]), _dc_oracle(mesh118), atol=1e-10
    )
    # Flows are the branch angle differences over x.
    flows = np.asarray(r.flows)
    k = mesh118.n_bus + 3
    f, t = int(mesh118.from_bus[k]), int(mesh118.to_bus[k])
    th = np.asarray(r.theta)
    assert flows[k] == pytest.approx((th[f] - th[t]) / mesh118.x[k])


def test_dc_outage_screen_matches_refactorization(mesh118):
    dcs = dc_mod.make_dc_solver(mesh118)
    ks = np.array([120, 127, 140, 160])
    r = dcs.screen_outages(jnp.asarray(ks))
    assert not bool(np.any(np.asarray(r.islanded)))
    for i, k in enumerate(ks):
        np.testing.assert_allclose(
            np.asarray(r.theta[i]), _dc_oracle(mesh118, outage=int(k)),
            atol=1e-9,
        )
        # The outaged branch carries nothing in its own lane.
        assert float(r.flows[i, k]) == 0.0
    assert np.all(np.isfinite(np.asarray(r.severity)))


def test_dc_bridge_outage_flagged_islanded():
    bt = np.array([SLACK, PQ, PQ])
    radial = BusSystem(
        bus_type=bt,
        p_inj=np.array([0.0, -0.5, -0.3]),
        q_inj=np.zeros(3),
        v_set=np.ones(3),
        g_shunt=np.zeros(3),
        b_shunt=np.zeros(3),
        from_bus=np.array([0, 1]),
        to_bus=np.array([1, 2]),
        r=np.array([0.01, 0.01]),
        x=np.array([0.1, 0.1]),
        b_chg=np.zeros(2),
        tap=np.ones(2),
        shift=np.zeros(2),
    ).validate()
    r = dc_mod.make_dc_solver(radial).screen_outages(jnp.asarray([1]))
    assert bool(r.islanded[0])
    assert np.isinf(float(r.severity[0]))


def test_dc_prefilter_excludes_islanding_bridges():
    # buses 0-1 by a bridge, 1-2-3 a triangle: outage 0 islands, the
    # triangle branches do not.
    bt = np.array([SLACK, PQ, PQ, PQ])
    sys_b = BusSystem(
        bus_type=bt,
        p_inj=np.array([0.0, -0.3, -0.4, -0.3]),
        q_inj=np.array([0.0, -0.1, -0.1, -0.1]),
        v_set=np.ones(4),
        g_shunt=np.zeros(4),
        b_shunt=np.zeros(4),
        from_bus=np.array([0, 1, 2, 3]),
        to_bus=np.array([1, 2, 3, 1]),
        r=np.full(4, 0.01),
        x=np.full(4, 0.1),
        b_chg=np.zeros(4),
        tap=np.ones(4),
        shift=np.zeros(4),
    ).validate()
    screen = make_n1_screen(sys_b, max_iter=24, dc_prefilter=2)
    out = screen(np.array([1, 2, 0]))
    # The bridge is flagged and skipped; the shortlist holds only
    # connectivity-preserving outages and its AC lanes all converge.
    np.testing.assert_array_equal(out.islanded, [False, False, True])
    assert 0 not in out.outages and out.outages.shape == (2,)
    assert np.all(np.isfinite(out.dc_severity))
    assert bool(np.all(np.asarray(out.result.converged)))
    # All-islanding request: typed error, not garbage lanes.
    with pytest.raises(ValueError, match="islands the network"):
        screen(np.array([0]))


def test_dc_prefilter_screens_top_k(mesh118):
    screen = make_n1_screen(mesh118, max_iter=24, dc_prefilter=4)
    ks = np.arange(118, 134)
    out = screen(ks)
    assert out.outages.shape == (4,)
    assert out.dc_severity_all.shape == (16,)
    # DC-worst first, drawn from the requested set, AC-verified.
    assert np.all(np.diff(out.dc_severity) <= 1e-12)
    assert set(out.outages) <= set(ks)
    assert float(out.dc_severity[0]) == pytest.approx(
        float(np.max(out.dc_severity_all))
    )
    assert bool(np.all(np.asarray(out.result.converged)))
    assert out.result.v.shape == (4, mesh118.n_bus)
    # The AC lanes really are the shortlisted outages: each matches the
    # plain screen's lane for that branch.
    plain = make_n1_screen(mesh118, max_iter=24)(jnp.asarray(out.outages))
    np.testing.assert_allclose(
        np.asarray(out.result.v), np.asarray(plain.v), atol=ATOL_V
    )


# ---------------------------------------------------------------------------
# QSTS: sparse backend matches dense within tolerance
# ---------------------------------------------------------------------------

_QSTS_SUMMARY_NUMERIC = (
    "violation_bus_minutes_mean", "violation_bus_minutes_max",
    "v_min_pu", "v_max_pu", "energy_loss_mwh_mean", "energy_loss_mwh_max",
    "peak_branch_mva",
)


def _qsts_summary(backend, mesh_devices=0, scenarios=4):
    from freedm_tpu.scenarios.engine import StudySpec, run_study

    return run_study(StudySpec(
        case="case14", scenarios=scenarios, steps=12, dt_minutes=15.0,
        chunk_steps=6, seed=3, pf_backend=backend,
        mesh_devices=mesh_devices,
    ))


def test_qsts_sparse_matches_dense():
    s_d = _qsts_summary("dense")
    s_s = _qsts_summary("sparse")
    assert s_d["pf_backend"] == "dense" and s_s["pf_backend"] == "sparse"
    assert s_s["lane_steps_not_converged"] == 0
    assert s_d["lane_steps_not_converged"] == 0
    for key in _QSTS_SUMMARY_NUMERIC:
        assert s_s[key] == pytest.approx(s_d[key], abs=1e-4), key


def test_qsts_backend_validated():
    from freedm_tpu.scenarios.engine import QstsEngine, StudySpec

    with pytest.raises(ValueError, match="unknown pf_backend"):
        QstsEngine(StudySpec(case="case14", pf_backend="bogus"))


# ---------------------------------------------------------------------------
# mesh composition: sparse lanes shard, pattern/preconditioner replicate
# ---------------------------------------------------------------------------


@needs_mesh
def test_sparse_mesh_matches_vmap(mesh118):
    from freedm_tpu.parallel.mesh import make_mesh

    lanes = 2 * D2
    rng = np.random.default_rng(1)
    scale = rng.uniform(0.9, 1.1, (lanes, 1))
    p = jnp.asarray(scale * mesh118.p_inj[None])
    q = jnp.asarray(scale * mesh118.q_inj[None])
    _, sp_fixed = make_sparse_newton_solver(mesh118, max_iter=8)
    r_ref = jax.jit(jax.vmap(
        lambda pi, qi: sp_fixed(p_inj=pi, q_inj=qi)
    ))(p, q)
    mesh = make_mesh(D2, axes=("batch",))
    _, sp_mesh = make_sparse_newton_solver(mesh118, max_iter=8, mesh=mesh)
    r_m = sp_mesh(p_inj=p, q_inj=q)
    # Sharded GEMM re-tiling moves Krylov iterates by ~eps (see
    # tests/test_mesh.py's module docstring); converged solutions stay
    # within solver tolerance.
    np.testing.assert_allclose(
        np.asarray(r_m.v), np.asarray(r_ref.v), atol=ATOL_V
    )
    np.testing.assert_allclose(
        np.asarray(r_m.theta), np.asarray(r_ref.theta), atol=ATOL_V
    )
    # Lane-count validation stays typed.
    with pytest.raises(ValueError, match="lane"):
        sp_mesh(p_inj=p[: D2 + 1])


@needs_mesh
def test_sparse_n1_mesh_screen_pads_ragged_lanes(mesh118):
    from freedm_tpu.core import profiling
    from freedm_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(D2, axes=("batch",))
    # The mesh path needs two solvers (sharded lanes + unsharded base
    # solve) but must build the preconditioner pair ONCE.
    profiling.PROFILER.configure(enabled=True)
    try:
        sp_mesh = make_n1_screen(mesh118, max_iter=24, backend="sparse",
                                 mesh=mesh)
        builds = profiling.PROFILER.snapshot()["host"].get(
            "sparse.precond_build", {"count": 0})["count"]
        assert builds == 1
    finally:
        profiling.PROFILER.reset()
    sp_ref = make_n1_screen(mesh118, max_iter=24, backend="sparse")
    ks = jnp.arange(118, 118 + 2 * D2 + 1)  # ragged: pads internally
    r_m, r_ref = sp_mesh(ks), sp_ref(ks)
    assert r_m.v.shape == r_ref.v.shape
    assert bool(np.all(np.asarray(r_m.converged)))
    np.testing.assert_allclose(
        np.asarray(r_m.v), np.asarray(r_ref.v), atol=ATOL_V
    )


@needs_mesh
def test_qsts_sparse_mesh_matches_unsharded():
    s_ref = _qsts_summary("sparse", scenarios=2 * D2)
    s_m = _qsts_summary("sparse", mesh_devices=D2, scenarios=2 * D2)
    assert s_m["mesh_devices"] == D2
    assert s_m["lane_steps_not_converged"] == 0
    for key in _QSTS_SUMMARY_NUMERIC:
        assert s_m[key] == pytest.approx(s_ref[key], abs=1e-4), key


# ---------------------------------------------------------------------------
# serve threading
# ---------------------------------------------------------------------------


def test_serve_rejects_unknown_backend():
    from freedm_tpu.serve import ServeConfig, Service

    with pytest.raises(ValueError, match="unknown pf_backend"):
        Service(ServeConfig(pf_backend="bogus"), start=False)


def test_serve_pf_engine_sparse_backend_answers():
    from freedm_tpu.serve import ServeConfig, Service
    from freedm_tpu.serve.service import PowerFlowRequest

    svc = Service(ServeConfig(max_batch=8, max_wait_ms=0.0,
                              pf_backend="sparse"))
    try:
        r = svc.request("pf", PowerFlowRequest(case="case14", scale=1.0))
        assert r.converged and r.residual_pu < 1e-6
        assert svc.stats()["pf_backend"] == "sparse"
    finally:
        svc.stop()
