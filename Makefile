# Developer/CI entry points.
#
#   make check   - static pass: byte-compile + pyflakes + gridlint + gridprobe
#   make test    - the tier-1 pytest line from ROADMAP.md
#
# `check` degrades gracefully when pyflakes is not installed (the
# runtime container does not ship it); CI installs it and gets the full
# lint.  gridlint (freedm_tpu/tools/gridlint.py) is stdlib-only, so it
# always runs — it enforces the project invariants pyflakes cannot see
# (jit purity, hot-path syncs, config/doc threading, lock order; see
# docs/static_analysis.md).  gridprobe (freedm_tpu/tools/gridprobe.py)
# audits the compiler IR of every registered jitted program (dtype
# flow, host transfers, constant capture, donation readiness) and
# diffs the checked-in program inventory; it needs jax, so it skips
# gracefully in a bare container the same way pyflakes does.

# `make test` uses `set -o pipefail`, which dash (the default /bin/sh on
# Debian-family systems) rejects.
SHELL := /bin/bash

PY ?= python

.PHONY: check compile lint gridlint gridprobe test

check: compile lint gridlint gridprobe

compile:
	$(PY) -m compileall -q freedm_tpu tests bench.py

lint:
	@if $(PY) -c "import pyflakes" 2>/dev/null; then \
		$(PY) -m pyflakes freedm_tpu tests bench.py; \
	else \
		echo "pyflakes not installed; skipping lint (compileall still ran)"; \
	fi

gridlint:
	$(PY) -m freedm_tpu.tools.gridlint freedm_tpu tests bench.py

gridprobe:
	@if $(PY) -c "import jax" 2>/dev/null; then \
		env JAX_PLATFORMS=cpu $(PY) -m freedm_tpu.tools.gridprobe; \
	else \
		echo "jax not installed; skipping gridprobe (gridlint still ran)"; \
	fi

test:
	set -o pipefail; rm -f /tmp/_t1.log; \
	timeout -k 10 870 env JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow' \
		--continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
		2>&1 | tee /tmp/_t1.log; rc=$$?; \
	echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log | tr -cd . | wc -c); \
	exit $$rc
